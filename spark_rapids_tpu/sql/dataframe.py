"""DataFrame API over logical plans (pyspark.sql.DataFrame surface).

Eager analysis (names resolve at call time, like pyspark), lazy execution.
``_execute`` runs the full pipeline: physical planning → TPU overrides
rewrite (plan/overrides.py) → partition pump → arrow collect.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import pyarrow as pa

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar import host as H
from spark_rapids_tpu.plan import analysis as AN
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.overrides import apply_overrides
from spark_rapids_tpu.plan.planner import plan_physical
from spark_rapids_tpu.sql.column import Column, UExpr, col as _col


class Row(tuple):
    """Lightweight pyspark.Row analog: tuple + field access."""

    def __new__(cls, values, fields):
        r = super().__new__(cls, values)
        r.__dict__ = {}
        r.__dict__["_fieldnames"] = fields
        return r

    def __getattr__(self, item):
        names = self.__dict__.get("_fieldnames", ())
        if item in names:
            return self[names.index(item)]
        raise AttributeError(item)

    def __getitem__(self, item):
        if isinstance(item, str):
            return self[self.__dict__["_fieldnames"].index(item)]
        return super().__getitem__(item)

    def asDict(self):
        return dict(zip(self.__dict__["_fieldnames"], self))

    def __repr__(self):
        names = self.__dict__.get("_fieldnames", ())
        inner = ", ".join(f"{n}={v!r}" for n, v in zip(names, self))
        return f"Row({inner})"


def _to_column(c: Union[str, Column]) -> Column:
    return _col(c) if isinstance(c, str) else c


class StructSpec:
    """One logical STRUCT column, physically stored flattened
    (struct-of-arrays — the TPU-native layout; arrow stores structs the
    same way).  ``fields``: [(field name, physical column name)];
    ``null_col``: physical bool column marking null structs (absent when
    the struct column has no nulls).
    [REF: complexTypeCreator.scala / cuDF struct columns — here structs
    are a FRONTEND view; every kernel sees plain columns]"""

    __slots__ = ("fields", "null_col")

    def __init__(self, fields, null_col=None):
        self.fields = list(fields)
        self.null_col = null_col

    @property
    def phys_cols(self):
        out = [p for _, p in self.fields]
        if self.null_col:
            out.append(self.null_col)
        return out

    def renamed(self, new_name: str) -> "StructSpec":
        return StructSpec(
            [(f, f"{new_name}.{f}") for f, _ in self.fields],
            f"{new_name}#null" if self.null_col else None)


class DataFrame:
    def __init__(self, session, plan: L.LogicalPlan, structs=None):
        self.session = session
        self._plan = plan
        # logical struct columns over the flattened physical schema
        self._structs: dict = dict(structs or {})

    def _derive(self, plan: L.LogicalPlan,
                structs="inherit") -> "DataFrame":
        """New frame over ``plan``; struct specs propagate when every
        physical column survived (schema-preserving ops), else pass the
        recomputed specs explicitly."""
        if structs == "inherit":
            names = set(plan.schema.field_names())
            structs = {k: v for k, v in self._structs.items()
                       if all(p in names for p in v.phys_cols)}
        return DataFrame(self.session, plan, structs)

    # every transformation body below constructs through this (a plain
    # textual stand-in for `DataFrame(self.session, ...)` that keeps
    # struct specs flowing)
    _derive_ctor = _derive

    @staticmethod
    def _adopt_structs(out: "DataFrame", other: "DataFrame"
                       ) -> "DataFrame":
        """Merge the right join side's struct specs into the result
        (kept only when every physical column survived)."""
        names = set(out.schema.field_names())
        for k, v in other._structs.items():
            if k not in out._structs and all(p in names
                                             for p in v.phys_cols):
                out._structs[k] = v
        return out

    # -- metadata -----------------------------------------------------------
    @property
    def schema(self) -> T.StructType:
        return self._plan.schema

    @property
    def columns(self) -> List[str]:
        return self.schema.field_names()

    def _struct_name_of(self, c) -> Optional[str]:
        """The struct-column name ``c`` denotes (bare string or a plain
        ``col('s')`` reference), else None."""
        if isinstance(c, str):
            return c if c in self._structs else None
        if isinstance(c, Column) and c._u.op == "attr" \
                and c._u.payload in self._structs:
            return c._u.payload
        return None

    def _expand_struct_names(self, cols):
        """Replace bare struct-column names/refs with their physical
        columns (null flag included — null structs group/sort as one
        value)."""
        out = []
        for c in cols:
            sname = self._struct_name_of(c)
            if sname is not None:
                out.extend(self._structs[sname].phys_cols)
            else:
                out.append(c)
        return out

    # -- transformations ----------------------------------------------------
    def select(self, *cols) -> "DataFrame":
        if any(self._generate_u(c) is not None for c in cols
               if not (isinstance(c, str) and c == "*")):
            return self._select_with_generate(cols)
        if any(self._pyudf_u(c) is not None for c in cols
               if not (isinstance(c, str) and c == "*")):
            if any(self._window_u(c) is not None for c in cols
                   if not (isinstance(c, str) and c == "*")):
                raise AN.AnalysisException(
                    "cannot mix python UDFs and window functions in one "
                    "select — materialize one of them first "
                    "(e.g. withColumn)")
            return self._select_with_pyudfs(cols)
        if any(self._window_u(c) is not None for c in cols
               if not (isinstance(c, str) and c == "*")):
            return self._select_with_windows(cols)
        from spark_rapids_tpu.ops.expressions import BoundReference
        exprs = []
        fields = []
        new_structs = {}

        def add_ref(name):
            i = self.schema.field_index(name)
            f = self.schema.fields[i]
            exprs.append(BoundReference(i, f.dtype, f.nullable))
            fields.append(f)

        for c in cols:
            if isinstance(c, str) and c == "*":
                for i, f in enumerate(self.schema.fields):
                    exprs.append(BoundReference(i, f.dtype, f.nullable))
                    fields.append(f)
                continue
            sname = self._struct_name_of(c)
            if sname is not None:
                # selecting a struct column = selecting its flattened
                # physical columns; the spec rides along
                spec = self._structs[sname]
                for p in spec.phys_cols:
                    add_ref(p)
                new_structs[sname] = spec
                continue
            u = _to_column(c)._u
            if (u.op == "alias" and u.children[0].op == "attr"
                    and u.children[0].payload in self._structs):
                # struct rename: re-emit the physical columns under the
                # new name's flattened layout
                spec = self._structs[u.children[0].payload]
                new = spec.renamed(u.payload)
                for (_, old_p), (_, new_p) in zip(spec.fields,
                                                  new.fields):
                    i = self.schema.field_index(old_p)
                    f = self.schema.fields[i]
                    exprs.append(BoundReference(i, f.dtype, f.nullable))
                    fields.append(T.StructField(new_p, f.dtype,
                                                f.nullable))
                if spec.null_col:
                    i = self.schema.field_index(spec.null_col)
                    f = self.schema.fields[i]
                    exprs.append(BoundReference(i, f.dtype, f.nullable))
                    fields.append(T.StructField(new.null_col, f.dtype,
                                                f.nullable))
                new_structs[u.payload] = new
                continue
            mk = u.children[0] if u.op == "alias" else u
            if mk.op == "make_struct":
                # F.struct(...): emit one physical column per field +
                # record the spec [REF: complexTypeCreator CreateStruct]
                sname = (u.payload if u.op == "alias"
                         else f"struct_{len(new_structs)}")
                sfields = []
                for fname, fu in zip(mk.payload, mk.children):
                    e = AN.resolve(fu, self.schema)
                    pname = f"{sname}.{fname}"
                    exprs.append(e)
                    fields.append(T.StructField(pname, e.dtype))
                    sfields.append((fname, pname))
                new_structs[sname] = StructSpec(sfields, None)
                continue
            u2 = self._rewrite_struct_ref(u)
            e = AN.resolve(u2, self.schema)
            name = self._output_name(u, e)
            exprs.append(e)
            fields.append(T.StructField(name, e.dtype))
        schema = T.StructType(tuple(fields))
        out = self._derive_ctor(L.Project(self._plan, exprs, schema))
        out._structs.update(new_structs)
        return out

    def _rewrite_struct_ref(self, u: UExpr) -> UExpr:
        """col('s') for a logical struct has no physical column; rewrite
        getField chains to the flattened name ('s'.getField('a') →
        attr 's.a')."""
        if u.op == "getfield":
            child = self._rewrite_struct_ref(u.children[0])
            if child.op == "attr":
                return UExpr("attr", f"{child.payload}.{u.payload}")
            raise AN.AnalysisException(
                "getField is only supported on (nested) column "
                "references")
        if not u.children:
            return u
        kids = tuple(self._rewrite_struct_ref(c) for c in u.children)
        if all(a is b for a, b in zip(kids, u.children)):
            return u
        return UExpr(u.op, u.payload, kids)

    @staticmethod
    def _generate_u(c) -> Optional[UExpr]:
        """The explode/posexplode UExpr under an optional alias."""
        if isinstance(c, str):
            return None
        u = _to_column(c)._u
        core = u.children[0] if u.op == "alias" else u
        return core if core.op == "generate" else None

    def _select_with_generate(self, cols) -> "DataFrame":
        """Spark's ExtractGenerator analog: one Generate node appends
        pos/element columns to the child, then a Project picks the
        requested output."""
        from spark_rapids_tpu.ops.expressions import BoundReference
        gens = [c for c in cols
                if not (isinstance(c, str) and c == "*")
                and self._generate_u(c) is not None]
        if len(gens) > 1:
            raise AN.AnalysisException(
                "only one generator (explode/posexplode) is allowed per "
                "select")
        base_schema = self.schema
        gu = self._generate_u(gens[0])
        with_pos, outer = gu.payload
        gen_expr = AN.resolve(gu.children[0], base_schema)
        if not isinstance(gen_expr.dtype, T.ArrayType):
            raise AN.AnalysisException(
                f"explode needs an array column, got "
                f"{gen_expr.dtype.simple_name}")
        alias_u = _to_column(gens[0])._u
        elem_name = (alias_u.payload if alias_u.op == "alias" else "col")
        elem_dt = gen_expr.dtype.element_type
        nc = len(base_schema)
        ext_fields = list(base_schema.fields)
        if with_pos:
            ext_fields.append(T.StructField("pos", T.IntegerT, outer))
        ext_fields.append(T.StructField(elem_name, elem_dt, True))
        ext_schema = T.StructType(tuple(ext_fields))
        plan = L.Generate(self._plan, gen_expr, with_pos, outer,
                          ext_schema)
        exprs, fields = [], []
        for c in cols:
            if isinstance(c, str) and c == "*":
                for i, f in enumerate(base_schema.fields):
                    exprs.append(BoundReference(i, f.dtype, f.nullable))
                    fields.append(f)
                continue
            if self._generate_u(c) is not None:
                if with_pos:
                    exprs.append(BoundReference(nc, T.IntegerT, outer))
                    fields.append(T.StructField("pos", T.IntegerT, outer))
                idx = nc + (1 if with_pos else 0)
                exprs.append(BoundReference(idx, elem_dt, True))
                fields.append(T.StructField(elem_name, elem_dt, True))
                continue
            u = _to_column(c)._u
            e = AN.resolve(u, ext_schema)
            exprs.append(e)
            fields.append(T.StructField(self._output_name(u, e), e.dtype))
        return self._derive_ctor( L.Project(
            plan, exprs, T.StructType(tuple(fields))))

    @staticmethod
    def _pyudf_u(c) -> Optional[UExpr]:
        if isinstance(c, str):
            return None
        u = _to_column(c)._u
        core = u.children[0] if u.op == "alias" else u
        return core if core.op == "pyudf" else None

    def _select_with_pyudfs(self, cols) -> "DataFrame":
        """Spark's ExtractPythonUDFs analog: one PythonEval node appends
        every UDF result column, then a Project picks the output.

        With ``spark.rapids.sql.udfCompiler.enabled`` the AST compiler
        first tries to lower each UDF onto the expression tree
        [REF: udf-compiler/ :: CatalystExpressionBuilder]; compiled UDFs
        become plain device expressions and skip the bridge entirely."""
        from spark_rapids_tpu import conf as C
        from spark_rapids_tpu.exec.python_udf import PyUDFSpec
        from spark_rapids_tpu.ops.expressions import BoundReference
        compile_enabled = bool(self.session.rapids_conf().get(
            C.UDF_COMPILER_ENABLED))
        base_schema = self.schema
        nc = len(base_schema)
        udfs = []
        out_specs = []
        for c in cols:
            if isinstance(c, str) and c == "*":
                out_specs.append(("plain", c))
                continue
            uu = self._pyudf_u(c)
            if uu is None:
                out_specs.append(("plain", c))
                continue
            fn, dt, vectorized, fname = uu.payload
            args = [AN.resolve(a, base_schema) for a in uu.children]
            u = _to_column(c)._u
            alias = u.payload if u.op == "alias" else None
            name = alias or f"{fname}({', '.join(map(str, args))})"
            if compile_enabled:
                from spark_rapids_tpu.sql.udf_compiler import (
                    UdfCompileError, compile_udf)
                try:
                    expr = compile_udf(fn, args, dt)
                    out_specs.append(("compiled", expr, name, dt))
                    continue
                except (UdfCompileError, AN.AnalysisException):
                    pass  # outside the subset → arrow bridge
            udfs.append(PyUDFSpec(fn, args, dt, vectorized, name))
            out_specs.append(("udf", len(udfs) - 1, name, dt))
        ext_fields = (list(base_schema.fields)
                      + [T.StructField(f"_udf{i}", u.dtype, True)
                         for i, u in enumerate(udfs)])
        ext_schema = T.StructType(tuple(ext_fields))
        plan = (L.PythonEval(self._plan, udfs, ext_schema) if udfs
                else self._plan)
        exprs, fields = [], []
        for spec in out_specs:
            if spec[0] == "plain":
                c = spec[1]
                if isinstance(c, str) and c == "*":
                    for i, f in enumerate(base_schema.fields):
                        exprs.append(BoundReference(i, f.dtype,
                                                    f.nullable))
                        fields.append(f)
                    continue
                u = _to_column(c)._u
                e = AN.resolve(u, ext_schema)
                exprs.append(e)
                fields.append(T.StructField(self._output_name(u, e),
                                            e.dtype))
            elif spec[0] == "compiled":
                _, e, name, dt = spec
                exprs.append(e)
                fields.append(T.StructField(name, dt, True))
            else:
                _, i, name, dt = spec
                exprs.append(BoundReference(nc + i, dt, True))
                fields.append(T.StructField(name, dt, True))
        return self._derive_ctor( L.Project(
            plan, exprs, T.StructType(tuple(fields))))

    def mapInPandas(self, fn, schema) -> "DataFrame":
        """fn(iterator[pandas.DataFrame]) → iterator[pandas.DataFrame]
        with the declared output schema [REF: GpuMapInPandasExec]."""
        if not isinstance(schema, T.StructType):
            raise AN.AnalysisException(
                "mapInPandas needs a StructType output schema")
        return self._derive_ctor(
                         L.MapInPandas(self._plan, fn, schema))

    @staticmethod
    def _window_u(c) -> Optional[UExpr]:
        """The window UExpr under an optional alias, else None."""
        if isinstance(c, str):
            return None
        u = _to_column(c)._u
        core = u.children[0] if u.op == "alias" else u
        return core if core.op == "window" else None

    def _select_with_windows(self, cols) -> "DataFrame":
        """Spark's ExtractWindowExpressions analog: insert Window plan
        nodes (one per distinct spec) that append the computed columns,
        then project the requested output."""
        from spark_rapids_tpu.ops.expressions import BoundReference
        base_schema = self.schema
        plan = self._plan
        appended = {}   # id(col-obj position) → (field index in extended)
        groups = {}     # spec-key → (pby, orders, [fns], [positions])
        out_specs = []  # per output col: ("plain", u) | ("win", pos_key)
        for ci, c in enumerate(cols):
            wu = self._window_u(c)
            if wu is None:
                out_specs.append(("plain", c))
                continue
            u = _to_column(c)._u
            pby, orders, wf, default_name = AN.resolve_window(
                wu, base_schema)
            alias = u.payload if u.op == "alias" else None
            skey = repr((wu.payload.partition_by, wu.payload.order_by,
                         wu.payload.frame))
            g = groups.setdefault(skey, (pby, orders, [], []))
            g[2].append(wf)
            g[3].append(ci)
            out_specs.append(("win", (skey, len(g[2]) - 1),
                             alias or default_name, wf.dtype))
        # build the Window chain; track where each group's outputs land
        offsets = {}
        ext_fields = list(base_schema.fields)
        wcount = 0
        for skey, (pby, orders, fns, _) in groups.items():
            offsets[skey] = len(ext_fields)
            new_fields = [
                T.StructField(f"_w{wcount + i}", fn.dtype)
                for i, fn in enumerate(fns)]
            wcount += len(fns)
            ext_fields.extend(new_fields)
            plan = L.Window(
                plan, pby, orders, fns,
                T.StructType(tuple(ext_fields)))
        ext_schema = T.StructType(tuple(ext_fields))
        # final projection over the extended schema
        exprs, fields = [], []
        for spec in out_specs:
            if spec[0] == "plain":
                c = spec[1]
                if isinstance(c, str) and c == "*":
                    for i, f in enumerate(base_schema.fields):
                        exprs.append(BoundReference(i, f.dtype,
                                                    f.nullable))
                        fields.append(f)
                    continue
                u = _to_column(c)._u
                e = AN.resolve(u, ext_schema)
                exprs.append(e)
                fields.append(T.StructField(self._output_name(u, e),
                                            e.dtype))
            else:
                (skey, j), name, dtype = spec[1], spec[2], spec[3]
                idx = offsets[skey] + j
                exprs.append(BoundReference(idx, dtype, True))
                fields.append(T.StructField(name, dtype))
        return self._derive_ctor( L.Project(
            plan, exprs, T.StructType(tuple(fields))))

    @staticmethod
    def _output_name(u: UExpr, e) -> str:
        if u.op == "alias":
            return u.payload
        if u.op == "attr":
            return u.payload
        return str(e)

    def _logical_columns(self) -> List[str]:
        """Column names as the user sees them: struct fields collapse to
        the struct name (positioned at its first physical field)."""
        out = []
        phys_to_struct = {}
        for sname, spec in self._structs.items():
            for p in spec.phys_cols:
                phys_to_struct[p] = sname
        seen = set()
        for n in self.columns:
            sname = phys_to_struct.get(n)
            if sname is None:
                out.append(n)
            elif sname not in seen:
                seen.add(sname)
                out.append(sname)
        return out

    def withColumn(self, name: str, c: Column) -> "DataFrame":
        cols = self._logical_columns()
        if name in cols:  # replace in place (pyspark semantics)
            return self.select(*[(c.alias(name) if n == name else n)
                                 for n in cols])
        return self.select(*cols, c.alias(name))

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        cols = [(_col(n).alias(new) if n == old else n)
                for n in self._logical_columns()]
        return self.select(*cols)

    def drop(self, *names) -> "DataFrame":
        keep = [n for n in self._logical_columns() if n not in names]
        return self.select(*keep)

    def filter(self, condition: Union[str, Column]) -> "DataFrame":
        if isinstance(condition, str):
            raise NotImplementedError("SQL-string filters not yet supported")
        cond = AN.resolve(self._rewrite_struct_ref(condition._u),
                          self.schema)
        if not isinstance(cond.dtype, (T.BooleanType, T.NullType)):
            raise AN.AnalysisException(
                f"filter condition must be boolean, got {cond.dtype}")
        return self._derive_ctor( L.Filter(self._plan, cond))

    where = filter

    def limit(self, n: int) -> "DataFrame":
        return self._derive_ctor( L.Limit(self._plan, n))

    def union(self, other: "DataFrame") -> "DataFrame":
        if len(other.schema) != len(self.schema):
            raise AN.AnalysisException("union: column count mismatch")
        return self._derive_ctor( L.Union([self._plan, other._plan]))

    unionAll = union

    def distinct(self) -> "DataFrame":
        return self.groupBy(*self.columns).agg()

    def sample(self, withReplacement=None, fraction=None, seed=None
               ) -> "DataFrame":
        """Bernoulli sample.  Accepts pyspark's signature variants:
        sample(fraction), sample(fraction, seed),
        sample(withReplacement, fraction, seed)."""
        if isinstance(withReplacement, float):
            # legacy form sample(fraction[, seed]): shift the arguments —
            # an explicit seed= keyword wins over the positional slot
            s2 = seed if seed is not None else fraction
            withReplacement, fraction, seed = (
                False, withReplacement, None if s2 is None else int(s2))
        if withReplacement:
            raise NotImplementedError(
                "sample(withReplacement=True) is not supported")
        if fraction is None or not (0.0 <= fraction <= 1.0):
            raise AN.AnalysisException(
                f"sample fraction must be in [0, 1], got {fraction}")
        if seed is None:
            import random
            seed = random.randint(0, 2**31 - 1)
        return self._derive_ctor(
                         L.Sample(self._plan, float(fraction), int(seed)))

    def repartition(self, num: int, *cols) -> "DataFrame":
        keys = [AN.resolve(_to_column(c)._u, self.schema) for c in cols] or None
        return self._derive_ctor(
                         L.Repartition(self._plan, num, keys))

    def groupBy(self, *cols) -> "GroupedData":
        exprs = []
        names = []
        for c in self._expand_struct_names(cols):
            u = self._rewrite_struct_ref(_to_column(c)._u)
            e = AN.resolve(u, self.schema)
            exprs.append(e)
            names.append(self._output_name(u, e))
        return GroupedData(self, exprs, names)

    groupby = groupBy

    def rollup(self, *cols) -> "GroupedData":
        """Hierarchical grouping sets: (a,b), (a), () for rollup(a, b).
        [REF: GpuExpandExec.scala — the reference accelerates Spark's
        Expand+Aggregate rollup plan; same shape here]"""
        g = self.groupBy(*cols)
        nk = len(g.grouping)
        g.sets = [list(range(k)) for k in range(nk, -1, -1)]
        return g

    def cube(self, *cols) -> "GroupedData":
        """All 2^n grouping-set combinations."""
        import itertools
        g = self.groupBy(*cols)
        nk = len(g.grouping)
        g.sets = [list(s) for r in range(nk, -1, -1)
                  for s in itertools.combinations(range(nk), r)]
        return g

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, [], []).agg(*aggs)

    def orderBy(self, *cols, ascending=None) -> "DataFrame":
        # ``ascending`` aligns with the USER's argument list; struct
        # expansion happens after, each field inheriting its struct's
        # direction (Spark orders structs field-lexicographically)
        pairs = []
        for i, c in enumerate(cols):
            a = (None if ascending is None
                 else (ascending[i] if isinstance(ascending, (list, tuple))
                       else bool(ascending)))
            sname = self._struct_name_of(c)
            if sname is not None:
                pairs.extend((p, a) for p in
                             self._structs[sname].phys_cols)
            else:
                pairs.append((c, a))
        orders = []
        for c, a in pairs:
            u = self._rewrite_struct_ref(_to_column(c)._u)
            asc, nulls_first = True, True
            if u.op == "sortorder":
                direction, nulls = u.payload
                asc = direction == "asc"
                nulls_first = nulls == "nulls_first"
                u = u.children[0]
            if a is not None:
                asc = a
                nulls_first = asc
            e = AN.resolve(u, self.schema)
            orders.append(L.SortOrder(e, asc, nulls_first))
        return self._derive_ctor( L.Sort(self._plan, orders))

    sort = orderBy

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = {"inner": "inner", "left": "left", "leftouter": "left",
               "left_outer": "left", "right": "right",
               "rightouter": "right", "right_outer": "right",
               "outer": "full", "full": "full", "fullouter": "full",
               "full_outer": "full", "semi": "left_semi",
               "leftsemi": "left_semi", "left_semi": "left_semi",
               "anti": "left_anti", "leftanti": "left_anti",
               "left_anti": "left_anti", "cross": "cross"}[how.lower()]
        if on is None:
            on = []
        if isinstance(on, str):
            on = [on]
        if isinstance(on, Column):
            return self._expression_join(other, on, how)
        left_keys, right_keys = [], []
        using = all(isinstance(c, str) for c in on)
        if not using:
            raise AN.AnalysisException(
                "join 'on' must be a column-name list or a single Column "
                "condition")
        for name in on:
            left_keys.append(AN.resolve(UExpr("attr", name), self.schema))
            right_keys.append(AN.resolve(UExpr("attr", name),
                                         other.schema))
        # output schema: USING semantics — join cols once (from left), then
        # remaining left cols, then remaining right cols
        fields: List[T.StructField] = []
        if using:
            for name in on:
                f = self.schema.fields[self.schema.field_index(name)]
                nullable = f.nullable or how in ("right", "full")
                fields.append(T.StructField(name, f.dtype, nullable))
            for f in self.schema.fields:
                if f.name not in on:
                    nullable = f.nullable or how in ("right", "full")
                    fields.append(T.StructField(f.name, f.dtype, nullable))
            if how not in ("left_semi", "left_anti"):
                for f in other.schema.fields:
                    if f.name not in on:
                        nullable = f.nullable or how in ("left", "full")
                        fields.append(T.StructField(f.name, f.dtype, nullable))
        schema = T.StructType(tuple(fields))
        out = self._derive_ctor(L.Join(
            self._plan, other._plan, how, left_keys, right_keys, None,
            schema))
        return self._adopt_structs(out, other)

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return self.join(other, on=[], how="cross")

    def _expression_join(self, other: "DataFrame", on: Column, how: str
                         ) -> "DataFrame":
        """Spark's ExtractEquiJoinKeys analog: resolve the condition
        against left++right, pull `left.col == right.col` conjuncts out
        as equi keys, keep the rest as a residual condition evaluated
        over the join output."""
        from spark_rapids_tpu.ops import expressions as E
        nl = len(self.schema)
        combined = T.StructType(tuple(self.schema.fields)
                                + tuple(other.schema.fields))
        cond = AN.resolve(on._u, combined)
        if not isinstance(cond.dtype, (T.BooleanType, T.NullType)):
            raise AN.AnalysisException(
                f"join condition must be boolean, got {cond.dtype}")

        conjuncts: List = []

        def split(e):
            if isinstance(e, E.And):
                split(e.left)
                split(e.right)
            else:
                conjuncts.append(e)

        split(cond)
        left_keys, right_keys, residual = [], [], []
        for c in conjuncts:
            sides = None
            if (isinstance(c, E.EqualTo)
                    and isinstance(c.left, E.BoundReference)
                    and isinstance(c.right, E.BoundReference)):
                li, ri = c.left.index, c.right.index
                if li < nl <= ri:
                    sides = (li, ri - nl)
                elif ri < nl <= li:
                    sides = (ri, li - nl)
            if sides is None:
                residual.append(c)
                continue
            li, ri = sides
            lf = self.schema.fields[li]
            rf = other.schema.fields[ri]
            left_keys.append(E.BoundReference(li, lf.dtype, lf.nullable))
            right_keys.append(E.BoundReference(ri, rf.dtype, rf.nullable))
        res = None
        for c in residual:
            res = c if res is None else E.And(res, c)
        if not left_keys and how not in ("inner", "cross"):
            raise AN.AnalysisException(
                f"{how} join requires at least one equi-join conjunct "
                "(left.col == right.col); got only a non-equi condition")
        # expression-join output: ALL left cols ++ ALL right cols
        semi = how in ("left_semi", "left_anti")
        fields: List[T.StructField] = []
        for f in self.schema.fields:
            nullable = f.nullable or how in ("right", "full")
            fields.append(T.StructField(f.name, f.dtype, nullable))
        if not semi:
            for f in other.schema.fields:
                nullable = f.nullable or how in ("left", "full")
                fields.append(T.StructField(f.name, f.dtype, nullable))
        out = self._derive_ctor(L.Join(
            self._plan, other._plan, how, left_keys, right_keys, res,
            T.StructType(tuple(fields)), using=False))
        return self._adopt_structs(out, other)

    # -- actions ------------------------------------------------------------
    def _execute_plan(self):
        from spark_rapids_tpu.plan.optimizer import optimize
        conf = self.session.rapids_conf()
        cpu = plan_physical(optimize(self._plan, conf), conf)
        result = apply_overrides(cpu, conf)
        self._last_override = result
        return result.plan

    def fallback_summary(self) -> dict:
        """Device-vs-fallback operator counts for the last planned
        execution (the reference's explain=NOT_ON_GPU signal as a
        metric [REF: ExplainPlanImpl; SURVEY §5.5])."""
        res = getattr(self, "_last_override", None)
        if res is None:
            self._execute_plan()
            res = self._last_override
        return res.fallback_summary()

    def toArrow(self, timeout_ms: Optional[float] = None,
                query_id: Optional[int] = None,
                cancel_token=None,
                tenant: Optional[str] = None) -> pa.Table:
        """Execute and return the result as an Arrow table.

        ``timeout_ms`` puts an in-process deadline on THIS execution
        (overriding ``spark.rapids.tpu.query.timeoutMs``): when it
        expires, every blocking boundary raises
        ``QueryCancelled(reason="deadline")`` and the engine reclaims
        the query's resources before the exception reaches the
        caller.

        ``query_id``/``cancel_token`` are the ``QueryServer``'s
        plumbing: the server mints the id and registers the token at
        *submit* time (so the query is cancellable while still queued
        for a run slot), then the admitted worker passes both here and
        the execution adopts them instead of minting fresh ones.
        ``tenant`` folds the tenant's conf overrides into the result
        key so tenants never share a cache slot.

        With ``spark.rapids.tpu.cache.enabled``, the result cache is
        consulted first: a hit hands back the resident Arrow table —
        no partition pump, no device semaphore — while still running
        the full query-window machinery, so the event-log entry
        carries ``cache.status="hit"`` with its usual telemetry/
        semaphore/stats attribution."""
        import contextlib
        import time as _time
        from spark_rapids_tpu import conf as C
        from spark_rapids_tpu.runtime import cancel as cancel_mod
        from spark_rapids_tpu.runtime import stats as stats_mod
        from spark_rapids_tpu.runtime import telemetry
        from spark_rapids_tpu.runtime import trace
        conf = self.session.rapids_conf()
        plan = self._execute_plan()
        self._last_plan = plan
        cache_store = ckey = None
        if conf.get(C.CACHE_ENABLED):
            from spark_rapids_tpu import cache as cache_mod
            cache_store = cache_mod.get_cache(conf)
            try:
                ckey = cache_mod.result_key(self._plan, plan, conf,
                                            tenant=tenant)
            except Exception:
                # unkeyable inputs (e.g. a vanished scan file) —
                # execute uncached
                cache_store = None
        qid = query_id if query_id is not None else trace.next_query_id()
        qwin = telemetry.begin_query(qid)
        from spark_rapids_tpu.runtime import resilience
        rwin = resilience.begin_query(qid)
        cwin = cancel_mod.begin_query(qid, conf, timeout_ms=timeout_ms,
                                      token=cancel_token)
        # the attribution plane rides the tracer: when attribution is on
        # (the default) the tracer runs even with trace.enabled off, but
        # _record_query only emits the rollup/chrome-trace artifacts the
        # user asked for — the spans feed the ledger + flight recorder
        from spark_rapids_tpu.runtime import attribution as attr_mod
        attr_on = bool(conf.get(C.ATTRIBUTION_ENABLED))
        tracer = None
        if conf.get(C.TRACE_ENABLED) or attr_on:
            tracer = trace.start_query(
                qid, max_events=int(conf.get(C.QUERY_LOG_MAX_EVENTS)))
        arec = None
        if attr_on:
            arec = attr_mod.start_query(
                qid, ring_size=int(conf.get(C.ATTRIBUTION_RING_SIZE)))
            if tracer is not None and arec is not None:
                tracer.recorder = arec
        collector = None
        if conf.get(C.STATS_ENABLED):
            collector = stats_mod.start_query(
                qid, level=str(conf.get(C.STATS_LEVEL)),
                skew_threshold=float(conf.get(C.STATS_SKEW_THRESHOLD)))
        profile = contextlib.nullcontext()
        profile_dir = None
        if conf.get(C.PROFILE_ENABLED):
            # per-query xplane capture, dump dir named after the query id
            # so trace + event-log entries cross-link
            # [REF: spark-rapids-jni profiler]
            import jax
            import os
            profile_dir = os.path.join(str(conf.get(C.PROFILE_PATH)),
                                       f"query-{qid:06d}")
            os.makedirs(profile_dir, exist_ok=True)
            profile = jax.profiler.trace(profile_dir)
        root = (tracer.span("Query", "execute")
                if tracer is not None else contextlib.nullcontext())
        error = None
        cancelled = None
        cache_info = None
        flight = None
        try:
            with profile, root:
                served = None
                if cache_store is not None:
                    with trace.span("ResultCache", "cacheProbe"):
                        served = cache_store.lookup(ckey.key)
                        if served is None:
                            role, fl = cache_store.join_flight(ckey.key)
                            if role == "leader":
                                flight = fl
                                fl.leader_qid = qid
                            else:
                                # another execution of this exact key is
                                # in progress — wait for it, then
                                # re-probe; compute ourselves if it
                                # failed or skipped
                                tok = cancel_mod.current()
                                while not fl.done.wait(0.05):
                                    cancel_mod.check()
                                    if tok is not None:
                                        tok.preempt_point()
                                    lq = fl.leader_qid
                                    lt = (cancel_mod.get_token(lq)
                                          if lq is not None else None)
                                    if (lt is not None
                                            and lt.preempt_pending()):
                                        # the leader was preempted
                                        # mid-flight; followers waiting
                                        # on it while holding run slots
                                        # would starve the scheduler of
                                        # the very slot the leader needs
                                        # to resume — break away and
                                        # compute independently
                                        break
                                served = cache_store.lookup(ckey.key)
                                if served is not None:
                                    cache_info = {"coalesced": True}
                if served is not None:
                    out = served.value
                    cache_info = {
                        "status": "hit", "key": served.key,
                        "signature": served.sig,
                        "bytes": served.nbytes,
                        "saved_s": round(served.runtime_s, 6),
                        "age_s": round(
                            _time.monotonic() - served.created, 6),
                        **(cache_info or {})}
                else:
                    t_exec = _time.perf_counter()
                    tables = self._pump_partitions(plan, conf)
                    with trace.span("Result", "concatTime"):
                        if not tables:
                            out = self._reassemble_structs(pa.table(
                                {f.name: pa.array(
                                    [], type=T.to_arrow(f.dtype))
                                 for f in self.schema.fields}))
                        else:
                            out = self._reassemble_structs(
                                pa.concat_tables(tables))
                    if cache_store is not None:
                        runtime_s = _time.perf_counter() - t_exec
                        cache_store.note_miss()
                        with trace.span("ResultCache", "cacheServe"):
                            stored = cache_store.put(
                                ckey, out, out.nbytes, runtime_s)
                        cache_info = {
                            "key": ckey.key, "signature": ckey.sig,
                            "bytes": out.nbytes,
                            "runtime_s": round(runtime_s, 6), **stored}
        except cancel_mod.QueryCancelled as e:
            cancelled = e
            error = f"{type(e).__name__}: {e}"
            # guaranteed reclamation: the cancelled pump abandoned its
            # registered spillables mid-flight — close them all so HBM
            # accounting unwinds and disk spill files are unlinked
            # (report_leaks() returns 0 after every cancelled query)
            from spark_rapids_tpu.runtime import memory
            mgr = memory.peek_manager()
            if mgr is not None:
                mgr.reclaim_all()
            raise
        except BaseException as e:
            error = f"{type(e).__name__}: {e}"
            raise
        finally:
            if flight is not None:
                # wake single-flight followers even on failure — they
                # re-probe and compute for themselves
                cache_store.finish_flight(ckey.key, flight)
            trace.end_query(tracer)
            stats_mod.end_query(collector)
            attr_mod.end_query(arec)
            cancel_mod.finish_query(cwin)
            self._record_query(qid, tracer, conf, profile_dir, error,
                               qwin, rwin, cancelled=cancelled,
                               ctoken=cwin, collector=collector,
                               cache_info=cache_info, recorder=arec)
        return out

    def _record_query(self, qid, tracer, conf, profile_dir, error,
                      qwin=None, rwin=None, cancelled=None, ctoken=None,
                      collector=None, cache_info=None, recorder=None):
        """One event-log entry per execution: plan tree, device/fallback
        report, all metrics at their levels, span rollup, artifact
        cross-links — the reference's driver-log plan-conversion report,
        machine-readable."""
        import time as _time
        from spark_rapids_tpu import conf as C
        from spark_rapids_tpu.runtime import trace
        plan = self._last_plan
        override = getattr(self, "_last_override", None)
        entry = {
            "query_id": qid,
            "ts": _time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "status": ("cancelled" if cancelled is not None
                       else "error" if error else "ok"),
            "plan": plan.tree_string(),
            "metrics": trace.plan_metrics(plan),
        }
        if error:
            entry["error"] = error
        if cache_info is not None:
            # result-cache outcome: status hit|stored|uncached, with
            # key/signature/bytes and saved_s (hit) or runtime_s (miss)
            entry["cache"] = cache_info
        if cancelled is not None:
            cinfo = {"reason": cancelled.reason}
            if ctoken is not None:
                if ctoken.latency_s is not None:
                    cinfo["latency_s"] = round(ctoken.latency_s, 6)
                if ctoken.detail:
                    cinfo["detail"] = ctoken.detail
            entry["cancel"] = cinfo
        if override is not None:
            entry["fallback"] = override.fallback_summary()
            entry["fallback_report"] = override.fallback_report()
        if tracer is not None and conf.get(C.TRACE_ENABLED):
            # tracing artifacts only when the user asked for tracing —
            # an attribution-only tracer feeds the ledger below but
            # must not start emitting rollups/chrome traces
            entry["wall_s"] = round(tracer.wall_s, 6)
            rollup = tracer.rollup()
            entry["op_rollup"] = rollup
            entry["dropped_spans"] = tracer.dropped
            self._last_rollup = rollup
            tf = trace.write_chrome_trace(
                str(conf.get(C.TRACE_PATH)), tracer)
            if tf:
                entry["trace_file"] = tf
        attribution = None
        if tracer is not None and conf.get(C.ATTRIBUTION_ENABLED):
            from spark_rapids_tpu.runtime import attribution as attr_mod
            attribution = attr_mod.attribute(
                tracer, tolerance=float(
                    conf.get(C.ATTRIBUTION_CLOSE_TOLERANCE)))
            entry["attribution"] = attribution
            attr_mod.note_unaccounted(attribution["unaccounted_s"])
        if profile_dir:
            entry["profile_dir"] = profile_dir
        lore = str(conf.get(C.LORE_TAG))
        if lore:
            entry["lore_tag"] = lore
        if qwin is not None:
            # process-counter deltas this query contributed + health
            # verdicts over them — cross-linked by the same query_id as
            # the trace/profile artifacts
            from spark_rapids_tpu.runtime import telemetry
            deltas, elapsed = qwin.finish()
            entry["telemetry"] = deltas
            health = telemetry.evaluate_health(deltas, elapsed, conf,
                                               query_id=qid)
            if health:
                entry["health"] = health
        from spark_rapids_tpu.runtime.semaphore import peek_semaphore
        sem = peek_semaphore()
        if sem is not None:
            # close THIS query's keyed stats window (opened by
            # telemetry.begin_query) — under concurrency the legacy
            # process-wide max_holders/wait_time bleed across queries,
            # the keyed window doesn't
            sw = sem.end_query_stats(qid)
            if sw is not None:
                entry["semaphore"] = {
                    "max_holders": sw["max_holders"],
                    "wait_s": round(sw["wait_time"], 6)}
        if rwin is not None:
            # retry/breaker/degradation rollup for the query's failure
            # domains (see runtime/resilience.py)
            from spark_rapids_tpu.runtime import resilience
            res = resilience.finish_query(rwin)
            if res is not None:
                entry["resilience"] = res
                # runtime degradations join the plan-time fallback
                # report: the same "what did NOT run on device" story,
                # one decided at planning, one at execution
                if res["degraded_ops"]:
                    entry.setdefault("fallback_report", []).extend(
                        f"!{d['op']} degraded to the host path at "
                        f"runtime [{d['domain']}] because {d['cause']}"
                        for d in res["degraded_ops"])
        if collector is not None:
            # the stats plane's profile record: per-op observed stats
            # keyed by stable plan-node signatures + exchange skew
            # summary, joined with the trace rollup's self-times
            from spark_rapids_tpu.runtime import stats as stats_mod
            profile = collector.report(
                plan, rollup=entry.get("op_rollup"),
                wall_s=entry.get("wall_s"))
            profile["ts"] = entry["ts"]
            profile["status"] = entry["status"]
            if attribution is not None:
                profile["attribution"] = attribution
            entry["op_stats"] = profile["ops"]
            if profile["exchanges"]:
                entry["exchange_stats"] = profile["exchanges"]
            if profile.get("adaptive_decisions"):
                entry["adaptive_decisions"] = (
                    profile["adaptive_decisions"])
            self._last_profile = profile
            self.session._last_profile = profile
            store = str(conf.get(C.STATS_STORE_PATH))
            if store:
                stats_mod.append_profile(store, profile)
        if recorder is not None:
            # bad exit -> leave the black box: the ring + ledger survive
            # the query that died.  Triggers: deadline kill, explicit
            # cancel, error, or a health WARN on an otherwise-ok run.
            trigger = None
            if cancelled is not None:
                trigger = ("timeout" if cancelled.reason == "deadline"
                           else "cancel")
            elif error:
                trigger = "error"
            elif entry.get("health"):
                trigger = "health"
            bb_dir = str(conf.get(C.ATTRIBUTION_BLACKBOX_PATH))
            if trigger is not None and bb_dir:
                from spark_rapids_tpu.runtime import (
                    attribution as attr_mod)
                extra = {k: entry[k] for k in
                         ("status", "error", "cancel", "health")
                         if entry.get(k)}
                path = attr_mod.dump_blackbox(
                    bb_dir, qid, trigger, attribution=attribution,
                    recorder=recorder, extra=extra,
                    max_dumps=int(conf.get(C.ATTRIBUTION_BLACKBOX_MAX)))
                if path:
                    entry["blackbox"] = path
        self._last_query_entry = entry
        self.session._record_query(entry)
        log_path = str(conf.get(C.QUERY_LOG_PATH))
        if log_path:
            trace.append_query_log(log_path, entry)

    def _reassemble_structs(self, t: pa.Table) -> pa.Table:
        """Physical flattened columns → logical arrow struct columns
        (the inverse of session._decompose_structs)."""
        if not self._structs:
            return t
        for sname, spec in self._structs.items():
            names = t.column_names
            if not all(p in names for _, p in spec.fields):
                continue
            def one_chunk(c):
                if isinstance(c, pa.ChunkedArray):
                    if c.num_chunks == 0:
                        return pa.array([], type=c.type)
                    return pa.concat_arrays(c.chunks)
                return c

            children = [one_chunk(t.column(p)) for _, p in spec.fields]
            mask = None
            if spec.null_col and spec.null_col in names:
                mask = pa.array(
                    one_chunk(t.column(spec.null_col)).to_pylist(),
                    pa.bool_())
            sa = pa.StructArray.from_arrays(
                children, names=[f for f, _ in spec.fields], mask=mask)
            pos = names.index(spec.fields[0][1])
            drop = {p for _, p in spec.fields}
            if spec.null_col:
                drop.add(spec.null_col)
            arrays, outnames = [], []
            inserted = False
            for n in names:
                if n == spec.fields[0][1]:
                    arrays.append(sa)
                    outnames.append(sname)
                    inserted = True
                if n in drop:
                    continue
                arrays.append(t.column(n))
                outnames.append(n)
            assert inserted
            t = pa.table(dict(zip(outnames, arrays)))
        return t

    @staticmethod
    def _pump_partitions(plan, conf) -> List[pa.Table]:
        """Execute every partition; partitions run on a thread pool (the
        Spark-task-slot analog) and device-touching plans must hold the
        admission semaphore [REF: GpuSemaphore.scala] — permits =
        ``spark.rapids.sql.concurrentGpuTasks``."""
        from spark_rapids_tpu.exec.base import TpuExec

        def has_device_work(node) -> bool:
            return isinstance(node, TpuExec) or any(
                has_device_work(c) for c in node.children)

        nparts = plan.num_partitions()
        on_device = has_device_work(plan)

        from spark_rapids_tpu.runtime import trace as trace_mod

        def pump(p: int) -> List[pa.Table]:
            # per-partition envelope span: charges iterator plumbing +
            # the root arrow conversion (time between instrumented
            # stages) to the pump_idle bucket — a no-op when neither
            # tracing nor attribution is active
            with trace_mod.span("PumpTask", "pumpTask",
                                {"partition": p}):
                return [H.to_arrow_table(b) for b in plan.execute(p)]

        if not on_device:
            out = []
            for p in range(nparts):
                out.extend(pump(p))
            return out

        from spark_rapids_tpu import conf as C
        from spark_rapids_tpu.runtime import cancel as cancel_mod
        from spark_rapids_tpu.runtime import scheduler as sched_mod
        waits: List[float] = []  # this query's waits only

        parts = list(range(nparts))
        from spark_rapids_tpu.parallel.executor import get_executor
        if get_executor() is not None:
            # multi-executor: every process must enter each collective
            # in the SAME order — materialize exchanges sequentially
            # (children-first = execution-dependency order) before the
            # parallel pump, then pump only partitions whose mesh device
            # is local to this process
            from spark_rapids_tpu.exec.distributed import (
                TpuIciShuffleExchangeExec, owned_partitions)

            def pre_materialize(node):
                for c in node.children:
                    pre_materialize(c)
                if isinstance(node, TpuIciShuffleExchangeExec):
                    node._materialize()

            with sched_mod.device_hold(conf, waited_out=waits):
                pre_materialize(plan)
            parts = owned_partitions(plan)

        # the query's cancel scope is thread-local — capture the token
        # here (the query thread) and re-bind it inside each pump-pool
        # worker so device admission stays cancellable and wait time
        # attributes to the right query under concurrency
        tok = cancel_mod.current()

        def task(p: int) -> List[pa.Table]:
            with cancel_mod.bind(tok), \
                    sched_mod.device_hold(conf, waited_out=waits):
                return pump(p)

        # a single task still holds a permit — a 1-partition query must
        # count against the concurrency cap like any other; the pump
        # pool records queue depth + per-task latency either way
        from spark_rapids_tpu.parallel.executor import run_pump_tasks
        permits = int(conf.get(C.CONCURRENT_TASKS) or 2)
        workers = min(len(parts), max(permits * 2, 4))
        chunks = run_pump_tasks(task, parts, max_workers=workers)
        plan.metric("semaphoreWaitTime").add(sum(waits))
        return [t for chunk in chunks for t in chunk]

    def metrics(self, level: Optional[str] = None):
        """Operator metrics of the last execution, filtered by
        ``spark.rapids.sql.metrics.level`` (or an explicit level)."""
        plan = getattr(self, "_last_plan", None)
        if plan is None:
            raise RuntimeError("no execution yet — run collect()/toArrow()")
        if level is None:
            from spark_rapids_tpu import conf as C
            level = self.session.rapids_conf().get(C.METRICS_LEVEL)
        return plan.collect_metrics(level=str(level))

    def collect(self, timeout_ms: Optional[float] = None) -> List[Row]:
        """Collect rows; ``timeout_ms`` deadlines the execution
        in-process (``QueryCancelled(reason="deadline")`` on expiry)."""
        tbl = self.toArrow(timeout_ms=timeout_ms)
        names = tuple(tbl.column_names)
        cols = [tbl.column(i).to_pylist() for i in range(tbl.num_columns)]
        return [Row(vals, names) for vals in zip(*cols)] if cols else []

    def count(self) -> int:
        return self.toArrow().num_rows

    def toPandas(self):
        return self.toArrow().to_pandas()

    def show(self, n: int = 20, truncate: bool = True):
        print(self.limit(n).toArrow().to_pandas().to_string())

    def explain(self, extended: bool = False):
        """``explain()`` prints the physical plan; ``explain(True)`` adds
        the fallback report; ``explain("metrics")`` prints the last
        execution's per-node metrics (at the configured level) and, when
        tracing was on, the per-operator self/total-time rollup;
        ``explain("analyze")`` EXECUTES the query if needed and prints
        the plan tree annotated with the observed per-operator stats
        (rows/batches/bytes, exchange skew) + trace self-times."""
        if isinstance(extended, str) and extended.lower() == "metrics":
            return self._explain_metrics()
        if isinstance(extended, str) and extended.lower() == "analyze":
            return self._explain_analyze()
        from spark_rapids_tpu.plan.optimizer import optimize
        conf = self.session.rapids_conf()
        cpu = plan_physical(optimize(self._plan, conf), conf)
        result = apply_overrides(cpu, conf)
        print(result.plan.tree_string())
        if extended:
            for line in result.fallback_report():
                print(line)

    def _explain_metrics(self):
        plan = getattr(self, "_last_plan", None)
        if plan is None:
            print("<no execution yet — run collect()/toArrow() first>")
            return
        print(plan.tree_string())
        for op, vals in self.metrics():
            shown = {k: (round(v, 6) if isinstance(v, float) else v)
                     for k, v in vals.items()}
            print(f"  {op}: {shown}")
        rollup = getattr(self, "_last_rollup", None)
        if rollup:
            print("-- per-op time attribution (traced) --")
            for op, r in sorted(rollup.items(),
                                key=lambda kv: -kv[1]["self_s"]):
                print(f"  {op}: self={r['self_s']:.6f}s "
                      f"total={r['total_s']:.6f}s spans={r['spans']}")

    @staticmethod
    def _fmt_bytes(n) -> str:
        n = float(n)
        for unit in ("B", "KiB", "MiB", "GiB"):
            if n < 1024 or unit == "GiB":
                return (f"{int(n)}{unit}" if unit == "B"
                        else f"{n:.1f}{unit}")
            n /= 1024
        return f"{n:.1f}GiB"

    def _explain_analyze(self):
        """EXPLAIN ANALYZE: run the query (with stats + tracing forced
        on when it has not executed with stats yet), then print the
        plan tree with each operator's observed statistics."""
        profile = getattr(self, "_last_profile", None)
        if profile is None:
            from spark_rapids_tpu import conf as C
            saved = {}
            for key in (C.STATS_ENABLED.key, C.TRACE_ENABLED.key):
                saved[key] = self.session.conf.get(key, None)
                self.session.conf.set(key, True)
            try:
                self.toArrow()
            finally:
                for key, old in saved.items():
                    if old is None:
                        self.session.conf.unset(key)
                    else:
                        self.session.conf.set(key, old)
            profile = getattr(self, "_last_profile", None)
        if profile is None:
            # a concurrent query owns the collector (nested execution)
            print("<stats unavailable — another query owns the stats "
                  "plane; re-run when it finishes>")
            return
        plan = self._last_plan
        # synthetic per-member records of fused regions carry their
        # PRE-fusion paths, which can collide with real nodes of the
        # fused tree — the tree walk wants only real-node records
        by_path = {r["path"]: r for r in profile["ops"]
                   if "fused_region" not in r}
        lines = []

        def walk(node, path, depth):
            rec = by_path.get(path, {})
            ann = (f"rows={rec.get('rows_out', 0)} "
                   f"batches={rec.get('batches_out', 0)} "
                   f"bytes={self._fmt_bytes(rec.get('bytes_out', 0))}")
            if rec.get("self_s") is not None:
                ann += (f" self={rec['self_s']:.6f}s"
                        f" total={rec['total_s']:.6f}s")
            parts = rec.get("partition_rows",
                            rec.get("partition_bytes"))
            if parts is not None:
                ann += (f" partitions={len(parts)}"
                        f" skew={rec.get('skew_factor', 1.0):.2f}")
                if rec.get("skewed"):
                    ann += " SKEWED"
                if rec.get("executors", 1) > 1:
                    ann += f" executors={rec['executors']}"
            if rec.get("fused"):
                ann += " fused"
            if rec.get("region_ops"):
                ann += f" region_ops={rec['region_ops']}"
                if rec.get("region_compile_s") is not None:
                    ann += f" compile={rec['region_compile_s']:.6f}s"
            if rec.get("kernel_backend"):
                ann += f" kernel={rec['kernel_backend']}"
            if rec.get("adaptive"):
                labels = []
                for d in rec["adaptive"]:
                    kind = d.get("kind")
                    if kind == "skew-split":
                        labels.extend(
                            f"skew-split({k})"
                            for k in d.get("splits", ()) or ("?",))
                    elif kind == "batch-retarget":
                        labels.append(
                            f"batch-retarget({d.get('target_rows')})")
                    else:
                        labels.append(str(kind))
                ann += " adaptive=" + ",".join(labels)
            lines.append("  " * depth
                         + ("*" if node.is_tpu else "")
                         + node.node_string() + f"  [{ann}]")
            for i, c in enumerate(node.children):
                walk(c, f"{path}.{i}", depth + 1)

        walk(plan, "0", 0)
        print("\n".join(lines))
        if profile.get("wall_s") is not None:
            print(f"-- wall {profile['wall_s']:.6f}s "
                  f"(query {profile['query_id']}, "
                  f"stats level {profile['level']}) --")

    @property
    def write(self):
        from spark_rapids_tpu.io.readers import DataFrameWriter
        return DataFrameWriter(self)


class GroupedData:
    def __init__(self, df: DataFrame, grouping, names):
        self.df = df
        self.grouping = grouping
        self.names = names
        self.sets = None  # grouping sets (rollup/cube); None = plain

    @staticmethod
    def _pandas_agg_u(a):
        u = _to_column(a)._u
        core = u.children[0] if u.op == "alias" else u
        if core.op == "pyudf" and core.payload[2]:  # vectorized
            return u, core
        return None

    def agg(self, *aggs) -> DataFrame:
        from spark_rapids_tpu.ops.aggregates import CountDistinct
        if any(self._pandas_agg_u(a) is not None for a in aggs):
            return self._agg_in_pandas(aggs)
        fns = []
        names = []
        for a in aggs:
            fn, name = AN.resolve_aggregate(_to_column(a)._u, self.df.schema)
            fns.append(fn)
            names.append(name)
        if any(isinstance(f, CountDistinct) for f in fns):
            if self.sets is not None:
                raise AN.AnalysisException(
                    "count(DISTINCT) under rollup/cube is not yet "
                    "supported")
            return self._agg_distinct(fns, names)
        if self.sets is not None:
            return self._agg_grouping_sets(fns, names)
        fields = [T.StructField(n, g.dtype)
                  for n, g in zip(self.names, self.grouping)]
        fields += [T.StructField(n, f.result_dtype)
                   for n, f in zip(names, fns)]
        schema = T.StructType(tuple(fields))
        return self.df._derive(L.Aggregate(
            self.df._plan, self.grouping, fns, schema))

    def _agg_in_pandas(self, aggs) -> DataFrame:
        """Grouped-aggregate pandas UDFs [REF: GpuAggregateInPandasExec]
        — lowered onto the grouped-map bridge: each agg fn(*series) →
        scalar runs per group inside one applyInPandas wrapper (device
        co-partitioning and the arrow bridge come for free)."""
        import pandas as pd
        if self.sets is not None:
            raise AN.AnalysisException(
                "pandas-UDF aggregates under rollup/cube are not "
                "supported")
        if not self.names:
            # global pandas-UDF aggregate: one row — lower by grouping
            # on a constant key, then drop it
            from spark_rapids_tpu.sql.functions import lit
            return (self.df.withColumn("__g", lit(0))
                    .groupBy("__g").agg(*aggs).drop("__g"))
        child_names = set(self.df.schema.field_names())
        for n in self.names:
            if n not in child_names:
                raise AN.AnalysisException(
                    "pandas-UDF aggregates need plain column grouping "
                    f"keys (got expression {n!r})")
        specs = []
        for i, a in enumerate(aggs):
            got = self._pandas_agg_u(a)
            if got is None:
                raise AN.AnalysisException(
                    "cannot mix pandas-UDF aggregates with built-in "
                    "aggregate functions in one agg() — split into two "
                    "aggregations and join")
            u, core = got
            fn, dt, _vec, fname = core.payload
            out_name = u.payload if u.op == "alias" else f"{fname}_{i}"
            arg_names = []
            for cu in core.children:
                if cu.op != "attr" or cu.payload not in child_names:
                    raise AN.AnalysisException(
                        "pandas-UDF aggregate arguments must be plain "
                        "columns (pre-compute expressions with "
                        "withColumn)")
                arg_names.append(cu.payload)
            specs.append((fn, dt, out_name, arg_names))
        key_names = list(self.names)
        by_name = {f.name: f for f in self.df.schema.fields}
        fields = [T.StructField(n, by_name[n].dtype) for n in key_names]
        fields += [T.StructField(n, dt) for _, dt, n, _ in specs]
        schema = T.StructType(tuple(fields))

        def wrapper(pdf):
            row = {k: [pdf[k].iloc[0]] for k in key_names}
            for fn, _dt, name, arg_names in specs:
                row[name] = [fn(*[pdf[an] for an in arg_names])]
            return pd.DataFrame(row)

        return self.applyInPandas(wrapper, schema)

    def _agg_grouping_sets(self, fns, names) -> DataFrame:
        """rollup/cube → Expand + Aggregate(keys + grouping id) + drop-gid
        Project — Spark's ResolveGroupingAnalytics plan shape, which the
        reference accelerates via GpuExpandExec."""
        from spark_rapids_tpu.ops.expressions import BoundReference, Literal
        child_schema = self.df.schema
        nc = len(child_schema)
        nk = len(self.grouping)
        projections = []
        for s in self.sets:
            inc = set(s)
            proj = [BoundReference(i, f.dtype, f.nullable)
                    for i, f in enumerate(child_schema.fields)]
            for i, g in enumerate(self.grouping):
                proj.append(g if i in inc else Literal(None, g.dtype))
            # Spark grouping_id: bit (nk-1-i) set when key i is NOT in
            # the grouping set
            gid = sum(1 << (nk - 1 - i) for i in range(nk)
                      if i not in inc)
            proj.append(Literal(gid, T.IntegerT))
            projections.append(proj)
        ex_fields = (list(child_schema.fields)
                     + [T.StructField(f"_g{i}", g.dtype, True)
                        for i, g in enumerate(self.grouping)]
                     + [T.StructField("_gid", T.IntegerT, False)])
        expand = L.Expand(self.df._plan, projections,
                          T.StructType(tuple(ex_fields)))
        grouping = [BoundReference(nc + i, g.dtype, True)
                    for i, g in enumerate(self.grouping)]
        grouping.append(BoundReference(nc + nk, T.IntegerT, False))
        agg_fields = ([T.StructField(n, g.dtype, True)
                       for n, g in zip(self.names, self.grouping)]
                      + [T.StructField("_gid", T.IntegerT, False)]
                      + [T.StructField(n, f.result_dtype)
                         for n, f in zip(names, fns)])
        agg = L.Aggregate(expand, grouping, fns,
                          T.StructType(tuple(agg_fields)))
        # final projection drops the grouping id
        out_fields = ([T.StructField(n, g.dtype, True)
                       for n, g in zip(self.names, self.grouping)]
                      + [T.StructField(n, f.result_dtype)
                         for n, f in zip(names, fns)])
        exprs = ([BoundReference(i, g.dtype, True)
                  for i, g in enumerate(self.grouping)]
                 + [BoundReference(nk + 1 + i, f.result_dtype)
                    for i, f in enumerate(fns)])
        return self.df._derive(L.Project(
            agg, exprs, T.StructType(tuple(out_fields))))

    def _agg_distinct(self, fns, names) -> DataFrame:
        """count(DISTINCT x): Spark's RewriteDistinctAggregates shape —
        a dedup groupby on (keys, x) feeding a plain count.

        [REF: Spark RewriteDistinctAggregates; the reference accelerates
        the same two-level plan]"""
        from spark_rapids_tpu.ops.aggregates import CountDistinct
        from spark_rapids_tpu.ops.expressions import BoundReference
        if not all(isinstance(f, CountDistinct) for f in fns):
            raise AN.AnalysisException(
                "mixing distinct and non-distinct aggregates in one "
                "agg() is not yet supported")
        if len(fns) != 1:
            raise AN.AnalysisException(
                "multiple count(DISTINCT) aggregates in one agg() are "
                "not yet supported")
        fn = fns[0]
        nk = len(self.grouping)
        inner_fields = [T.StructField(f"k{i}", g.dtype)
                        for i, g in enumerate(self.grouping)]
        inner_fields.append(T.StructField("_dv", fn.child.dtype))
        inner_schema = T.StructType(tuple(inner_fields))
        inner = L.Aggregate(self.df._plan,
                            list(self.grouping) + [fn.child], [],
                            inner_schema)
        from spark_rapids_tpu.ops.aggregates import Count
        outer_grouping = [BoundReference(i, g.dtype)
                          for i, g in enumerate(self.grouping)]
        outer_fn = Count(BoundReference(nk, fn.child.dtype))
        fields = [T.StructField(n, g.dtype)
                  for n, g in zip(self.names, self.grouping)]
        fields.append(T.StructField(names[0], T.LongT))
        schema = T.StructType(tuple(fields))
        return self.df._derive(L.Aggregate(
            inner, outer_grouping, [outer_fn], schema))

    def count(self) -> DataFrame:
        from spark_rapids_tpu.sql import functions as F
        return self.agg(F.count("*").alias("count"))

    def applyInPandas(self, fn, schema) -> DataFrame:
        """Grouped-map pandas UDF: fn(pandas.DataFrame) → DataFrame per
        group.  Rides a hash exchange on the keys so one group never
        splits [REF: GpuFlatMapGroupsInPandasExec]."""
        from spark_rapids_tpu.ops.expressions import BoundReference
        if not isinstance(schema, T.StructType):
            raise AN.AnalysisException(
                "applyInPandas needs a StructType output schema")
        if self.sets is not None:
            raise AN.AnalysisException(
                "applyInPandas is not supported under rollup/cube")
        key_indices = []
        for g in self.grouping:
            if not isinstance(g, BoundReference):
                raise AN.AnalysisException(
                    "applyInPandas grouping keys must be plain columns")
            key_indices.append(g.index)
        nparts = self.df.session.rapids_conf().shuffle_partitions
        shuffled = L.Repartition(self.df._plan, nparts,
                                 list(self.grouping))
        return DataFrame(self.df.session, L.FlatMapGroupsInPandas(
            shuffled, key_indices, fn, schema))

    def _simple(self, kind, *cols):
        from spark_rapids_tpu.sql import functions as F
        targets = cols or [
            n for n in self.df.columns
            if T.is_numeric(self.df.schema.fields[
                self.df.schema.field_index(n)].dtype)
            and n not in self.names]
        fn = getattr(F, kind)
        return self.agg(*[fn(_col(c)).alias(f"{kind}({c})") for c in targets])

    def sum(self, *cols):
        return self._simple("sum", *cols)

    def min(self, *cols):
        return self._simple("min", *cols)

    def max(self, *cols):
        return self._simple("max", *cols)

    def avg(self, *cols):
        return self._simple("avg", *cols)

    mean = avg


from spark_rapids_tpu.sql.column import col  # noqa: E402,F401  (re-export)
