"""pyspark.sql.functions-compatible function surface (growing)."""

from __future__ import annotations

from spark_rapids_tpu.sql.column import Column, UExpr, _to_uexpr, col, lit  # noqa: F401


def _cu(c) -> UExpr:
    """Function-argument conversion: bare strings are column names
    (pyspark.sql.functions semantics), everything else like _to_uexpr."""
    if isinstance(c, str):
        return UExpr("attr", c)
    return _to_uexpr(c)


def _unary(op):
    def fn(c) -> Column:
        return Column(UExpr(op, None, (_cu(c),)))
    fn.__name__ = op
    return fn


def _binary(op):
    def fn(a, b) -> Column:
        return Column(UExpr(op, None, (_cu(a), _cu(b))))
    fn.__name__ = op
    return fn


sqrt = _unary("sqrt")
exp = _unary("exp")
log = _unary("log")
abs = _unary("abs")  # noqa: A001
floor = _unary("floor")
ceil = _unary("ceil")
year = _unary("year")
month = _unary("month")
dayofmonth = _unary("dayofmonth")
upper = _unary("upper")
lower = _unary("lower")
length = _unary("length")
isnan = _unary("isnan")
trim = _unary("trim")
ltrim = _unary("ltrim")
rtrim = _unary("rtrim")

def from_utc_timestamp(c, tz: str) -> Column:
    return Column(UExpr("from_utc_timestamp", tz, (_cu(c),)))


def to_utc_timestamp(c, tz: str) -> Column:
    return Column(UExpr("to_utc_timestamp", tz, (_cu(c),)))


pow = _binary("pow")  # noqa: A001
date_add = _binary("date_add")
date_sub = _binary("date_sub")
datediff = _binary("datediff")
concat = None  # set below (variadic)


def round(c, scale=0) -> Column:  # noqa: A001
    return Column(UExpr("round", scale, (_cu(c),)))


def coalesce(*cols) -> Column:
    return Column(UExpr("coalesce", None, tuple(_cu(c) for c in cols)))


def when(cond: Column, value) -> Column:
    return Column(UExpr("casewhen", None,
                        (_to_uexpr(cond), _to_uexpr(value))))


def substring(c, pos, length) -> Column:
    return Column(UExpr("substring", (pos, length), (_cu(c),)))


def concat_impl(*cols) -> Column:
    return Column(UExpr("concat", None, tuple(_cu(c) for c in cols)))


concat = concat_impl


def hash(*cols) -> Column:  # noqa: A001
    """Spark murmur3 hash (seed 42)."""
    return Column(UExpr("hash", None, tuple(_cu(c) for c in cols)))


def xxhash64(*cols) -> Column:
    """Spark xxhash64 (seed 42) → long."""
    return Column(UExpr("xxhash64", None, tuple(_cu(c) for c in cols)))


def struct(*cols) -> Column:
    """Create a STRUCT column [REF: complexTypeCreator CreateStruct].
    Physically lowered to one flattened column per field (the
    struct-of-arrays layout every kernel already speaks)."""
    names = []
    kids = []
    for i, c in enumerate(cols):
        if isinstance(c, str):
            names.append(c.split(".")[-1])
            kids.append(UExpr("attr", c))
            continue
        u = _cu(c)
        if u.op == "alias":
            names.append(u.payload)
        elif u.op == "attr":
            names.append(str(u.payload).split(".")[-1])
        else:
            names.append(f"col{i + 1}")
        kids.append(u.children[0] if u.op == "alias" else u)
    return Column(UExpr("make_struct", tuple(names), tuple(kids)))


def get_json_object(c, path: str) -> Column:
    """Extract a JSON path from a JSON string column (host-evaluated;
    the subtree reports NOT_ON_TPU until the device JSON scanner
    lands)."""
    return Column(UExpr("get_json_object", path, (_cu(c),)))


def rlike(c, pattern: str) -> Column:
    return Column(UExpr("rlike", pattern, (_cu(c),)))


def regexp_extract(c, pattern: str, idx: int = 1) -> Column:
    return Column(UExpr("regexp_extract", (pattern, idx), (_cu(c),)))


def regexp_replace(c, pattern: str, replacement: str) -> Column:
    return Column(UExpr("regexp_replace", (pattern, replacement),
                        (_cu(c),)))


def split(c, pattern: str, limit: int = -1) -> Column:
    return Column(UExpr("split", (pattern, limit), (_cu(c),)))


def reverse(c) -> Column:
    return Column(UExpr("reverse", None, (_cu(c),)))


def lpad(c, length: int, pad: str = " ") -> Column:
    return Column(UExpr("lpad", (length, pad), (_cu(c),)))


def rpad(c, length: int, pad: str = " ") -> Column:
    return Column(UExpr("rpad", (length, pad), (_cu(c),)))


def replace(c, search: str, replacement: str) -> Column:
    """replace(str, search, replace) with literal search/replace."""
    return Column(UExpr("replace", (search, replacement), (_cu(c),)))


def instr(c, substr: str) -> Column:
    """1-based position of the first occurrence; 0 if absent."""
    return Column(UExpr("locate", 1, (UExpr("lit", substr), _cu(c))))


def locate(substr: str, c, pos: int = 1) -> Column:
    return Column(UExpr("locate", pos, (UExpr("lit", substr), _cu(c))))


# aggregate functions -------------------------------------------------------

def _agg(op):
    def fn(c) -> Column:
        return Column(UExpr("agg", op, (_cu(c),)))
    fn.__name__ = op
    return fn


sum = _agg("sum")  # noqa: A001
min = _agg("min")  # noqa: A001
max = _agg("max")  # noqa: A001
avg = _agg("avg")
mean = _agg("avg")
first = _agg("first")


def count(c) -> Column:
    if isinstance(c, str) and c == "*":
        return Column(UExpr("agg", "count_star", (UExpr("lit", 1),)))
    return Column(UExpr("agg", "count", (_cu(c),)))


def countDistinct(c) -> Column:
    return Column(UExpr("agg", "count_distinct", (_cu(c),)))


count_distinct = countDistinct


def approx_count_distinct(c, rsd: float = 0.05) -> Column:
    """approx_count_distinct [REF: GpuApproximateCountDistinct /
    spark-rapids-jni HLL++].  Implemented EXACTLY via the two-level
    distinct-aggregate rewrite: an exact count trivially satisfies any
    ``rsd`` error bound.  The HLL++ sketch (whose value is mergeable
    fixed-size buffers for huge-cardinality distributed merges) is a
    planned optimization, not a semantics change."""
    if not (0.0 <= float(rsd) < 1.0):
        raise ValueError(f"rsd must be in [0, 1), got {rsd}")
    return countDistinct(c)


def _agg1(kind):
    def fn(c) -> Column:
        return Column(UExpr("agg", kind, (_cu(c),)))
    fn.__name__ = kind
    return fn


var_samp = _agg1("var_samp")
var_pop = _agg1("var_pop")
stddev_samp = _agg1("stddev_samp")
stddev_pop = _agg1("stddev_pop")
variance = var_samp
stddev = stddev_samp
collect_list = _agg1("collect_list")
collect_set = _agg1("collect_set")


def percentile(c, pct: float) -> Column:
    return Column(UExpr("agg", ("percentile", float(pct)), (_cu(c),)))


def percentile_approx(c, pct: float, accuracy: int = 10000) -> Column:
    return Column(UExpr("agg", ("approx_percentile", float(pct),
                                int(accuracy)), (_cu(c),)))


approx_percentile = percentile_approx


# python UDFs ---------------------------------------------------------------

def _make_udf(f, returnType, vectorized: bool):
    from spark_rapids_tpu.columnar import dtypes as T
    from spark_rapids_tpu.plan.analysis import _parse_type
    dt = (returnType if isinstance(returnType, T.DataType)
          else _parse_type(returnType))

    def call(*cols) -> Column:
        name = getattr(f, "__name__", "udf")
        return Column(UExpr("pyudf", (f, dt, vectorized, name),
                            tuple(_cu(c) for c in cols)))

    call.__name__ = getattr(f, "__name__", "udf")
    return call


def device_udf(f=None, returnType="double"):
    """Columnar DEVICE UDF [REF: spark-rapids RapidsUDF]: ``f`` receives
    the argument columns' raw device arrays (jax) and returns the result
    array — it executes INSIDE the fused XLA program of the surrounding
    expression tree (no launch boundary, no host round trip).  Also
    usable as ``@device_udf(returnType=...)``.  Numeric/boolean/datetime
    columns; nulls propagate as intersected validity."""
    from spark_rapids_tpu.columnar import dtypes as T
    from spark_rapids_tpu.plan.analysis import _parse_type

    def make(fn):
        dt = (returnType if isinstance(returnType, T.DataType)
              else _parse_type(returnType))

        def call(*cols) -> Column:
            name = getattr(fn, "__name__", "device_udf")
            return Column(UExpr("device_udf", (fn, dt, name),
                                tuple(_cu(c) for c in cols)))

        call.__name__ = getattr(fn, "__name__", "device_udf")
        return call

    if f is None or not callable(f):
        if f is not None:
            returnType = f
        return make
    return make(f)


def udf(f=None, returnType="string"):
    """Row-at-a-time python UDF (also usable as @udf(returnType=...)).
    [REF: GpuRowBasedScalaUDF analog — runs host-side over the arrow
    bridge, args computed on device]"""
    if f is None or not callable(f):
        rt = returnType if f is None else f
        return lambda fn: _make_udf(fn, rt, False)
    return _make_udf(f, returnType, False)


def pandas_udf(f=None, returnType="double"):
    """Vectorized pandas UDF (Series → Series)."""
    if f is None or not callable(f):
        rt = returnType if f is None else f
        return lambda fn: _make_udf(fn, rt, True)
    return _make_udf(f, returnType, True)


def input_file_name() -> Column:
    """File path of the current row's source file (file scans only)."""
    return Column(UExpr("input_file_name", None))


# generators ----------------------------------------------------------------

def explode(c) -> Column:
    return Column(UExpr("generate", (False, False), (_cu(c),)))


def explode_outer(c) -> Column:
    return Column(UExpr("generate", (False, True), (_cu(c),)))


def posexplode(c) -> Column:
    return Column(UExpr("generate", (True, False), (_cu(c),)))


def posexplode_outer(c) -> Column:
    return Column(UExpr("generate", (True, True), (_cu(c),)))


# window functions ----------------------------------------------------------

def row_number() -> Column:
    return Column(UExpr("winfn", ("row_number",)))


def rank() -> Column:
    return Column(UExpr("winfn", ("rank",)))


def dense_rank() -> Column:
    return Column(UExpr("winfn", ("dense_rank",)))


def lag(c, offset: int = 1, default=None,
        ignorenulls: bool = False) -> Column:
    if default is not None:
        raise NotImplementedError("lag default value not supported")
    return Column(UExpr("winfn", ("lag", offset, ignorenulls),
                        (_cu(c),)))


def lead(c, offset: int = 1, default=None,
         ignorenulls: bool = False) -> Column:
    if default is not None:
        raise NotImplementedError("lead default value not supported")
    return Column(UExpr("winfn", ("lead", offset, ignorenulls),
                        (_cu(c),)))


def ntile(n: int) -> Column:
    return Column(UExpr("winfn", ("ntile", int(n))))


def percent_rank() -> Column:
    return Column(UExpr("winfn", ("percent_rank",)))


def cume_dist() -> Column:
    return Column(UExpr("winfn", ("cume_dist",)))
