"""Shim layer — one engine over multiple jax/runtime versions.

[REF: sql-plugin-api/../ShimLoader.scala, per-version SparkShimImpl;
 SURVEY §2.1 #2] — the reference ships one jar supporting many Spark
versions through service-provider shims picked by version at runtime.
This engine's moving substrate is jax/XLA rather than Spark, so the
same mechanism binds here: a ``Shim`` provider per supported jax
version range, selected once at import, carrying every
version-sensitive behavior behind a stable interface.  Adding support
for a new jax release = adding a provider, not editing call sites.

Current hooks (each one exists because call sites genuinely vary or
have varied across jax releases):
* ``async_copy_to_host(buf)`` — overlapped D2H prefetch
  (``copy_to_host_async``; a no-op provider keeps older/exotic array
  types working — the try/except that previously lived at call sites).
* ``stable_argsort(x)`` — stable ascending argsort (the ``stable=``
  kwarg is newer than some supported versions).
"""

from __future__ import annotations

from typing import Optional


class Shim:
    """Base provider — implements hooks for the newest supported jax."""

    version_range = ("0.5", None)  # [min, max) — None = open-ended
    name = "jax-current"

    def async_copy_to_host(self, buf) -> bool:
        """Start an async D2H copy; False when unsupported for buf."""
        try:
            buf.copy_to_host_async()
            return True
        except AttributeError:
            return False

    def stable_argsort(self, x):
        import jax.numpy as jnp
        return jnp.argsort(x, stable=True)


class LegacyJaxShim(Shim):
    """jax < 0.5: no ``stable=`` kwarg on ``jnp.argsort`` — go through
    ``lax.sort`` (stable variadic sort, API constant across versions)."""

    version_range = ("0.4", "0.5")
    name = "jax-legacy-0.4"

    def stable_argsort(self, x):
        import jax
        import jax.numpy as jnp
        iota = jnp.arange(x.shape[0], dtype=jnp.int32)
        _, perm = jax.lax.sort((x, iota), num_keys=1, is_stable=True)
        return perm


_PROVIDERS = [Shim, LegacyJaxShim]
_active: Optional[Shim] = None


def _version_tuple(v: str):
    out = []
    for part in v.split(".")[:3]:
        digits = "".join(ch for ch in part if ch.isdigit())
        out.append(int(digits) if digits else 0)
    return tuple(out)


def _in_range(version: str, rng) -> bool:
    lo, hi = rng
    v = _version_tuple(version)
    if lo is not None and v < _version_tuple(lo):
        return False
    if hi is not None and v >= _version_tuple(hi):
        return False
    return True


def get_shim() -> Shim:
    """Select the provider matching the running jax version (cached).

    [REF: ShimLoader.getShimVersion — same pick-by-version contract]"""
    global _active
    if _active is None:
        import jax
        for cls in _PROVIDERS:
            if _in_range(jax.__version__, cls.version_range):
                _active = cls()
                break
        else:
            raise RuntimeError(
                f"no shim provider for jax {jax.__version__}; supported "
                f"ranges: {[c.version_range for c in _PROVIDERS]}")
    return _active


def reset_shim() -> None:
    global _active
    _active = None
