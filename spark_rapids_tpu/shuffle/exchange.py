"""MULTITHREADED shuffle exchange exec — the host-path transport.

[REF: sql-plugin/../RapidsShuffleInternalManagerBase.scala ::
 RapidsShuffleThreadedWriter/Reader; GpuShuffleExchangeExecBase] — the
reference's default shuffle: device batches are serialized on a thread
pool into shuffle files and reduce tasks deserialize their sections.
Map side here: partition ids are computed ON DEVICE with the bit-exact
Spark murmur3 kernel (same kernel as the in-process exchange), batches
come to host once (D2H), and the native tudo serializer gather-writes
every partition's rows in one threaded pass.  Reduce side: seek-read the
partition's sections, host-concat (numpy views), one H2D per partition.

This is the works-everywhere transport (no mesh needed) and the wire
format the multi-executor rendezvous uses for its DCN fallback.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Iterator, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import (
    DeviceBatch, DeviceColumn, round_up_pow2)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.ops.expressions import Expression
from spark_rapids_tpu.runtime import cancel
from spark_rapids_tpu.runtime import resilience as R
from spark_rapids_tpu.runtime import stats
from spark_rapids_tpu.runtime import telemetry as TM
from spark_rapids_tpu.shuffle.manager import (
    ShuffleEnv, ShuffleReader, ShuffleWriter)
from spark_rapids_tpu.shuffle.serializer import HostColView

_TM_EXCHANGES = TM.REGISTRY.counter(
    "tpuq_shuffle_exchanges_total",
    "host-shuffle exchanges materialized")
_TM_PARTITIONS = TM.REGISTRY.counter(
    "tpuq_shuffle_partitions_total",
    "reduce partitions produced by materialized exchanges")
_TM_WRITE_S = TM.REGISTRY.counter(
    "tpuq_shuffle_write_seconds_total",
    "host-shuffle map-side write/serialize seconds")
_TM_READ_S = TM.REGISTRY.counter(
    "tpuq_shuffle_read_seconds_total",
    "host-shuffle reduce-side read/deserialize seconds")


def _host_views(batch: DeviceBatch) -> List[HostColView]:
    """D2H every column of a device batch as serializable views."""
    out = []
    for c in batch.columns:
        data = np.asarray(c.data)
        validity = None if c.validity is None else np.asarray(c.validity)
        lengths = None if c.lengths is None else np.asarray(c.lengths)
        out.append(HostColView(c.dtype, data, validity, lengths))
    return out


def _concat_views(schema: T.StructType, records) -> tuple:
    """Concat deserialized records host-side → (nrows, HostColView list)."""
    records = list(records)
    if not records:
        return 0, None
    if len(records) == 1:
        return records[0]
    total = sum(n for n, _ in records)
    cols: List[HostColView] = []
    for ci, f in enumerate(schema.fields):
        parts = [r[1][ci] for r in records]
        any_val = any(p.validity is not None for p in parts)
        if parts[0].is_string:
            width = max(max(int(p.data.shape[1]) for p in parts), 1)
            mats = []
            for p, (n, _) in zip(parts, records):
                m = p.data[:n]
                if m.shape[1] < width:
                    m = np.pad(m, ((0, 0), (0, width - m.shape[1])))
                mats.append(m)
            data = np.concatenate(mats)
            lengths = np.concatenate(
                [p.lengths[:n] for p, (n, _) in zip(parts, records)])
        else:
            data = np.concatenate(
                [p.data[:n] for p, (n, _) in zip(parts, records)])
            lengths = None
        validity = None
        if any_val:
            validity = np.concatenate([
                (p.validity[:n] if p.validity is not None
                 else np.ones(n, np.uint8))
                for p, (n, _) in zip(parts, records)])
        cols.append(HostColView(f.dtype, data, validity, lengths))
    return total, cols


def _to_device(schema: T.StructType, cols: List[HostColView], n: int,
               min_bucket: int) -> DeviceBatch:
    """Host column views → padded static-shape DeviceBatch (one H2D)."""
    cap = round_up_pow2(max(n, 1), min_bucket)
    dcols = []
    for f, c in zip(schema.fields, cols):
        if c.is_string:
            w = max(int(c.data.shape[1]), 1)
            mat = np.zeros((cap, w), np.uint8)
            mat[:n] = c.data[:n]
            data = jnp.asarray(mat)
            lengths = np.zeros(cap, np.int32)
            lengths[:n] = c.lengths[:n]
            lengths = jnp.asarray(lengths)
        else:
            buf = np.zeros(cap, c.data.dtype)
            buf[:n] = c.data[:n]
            data = jnp.asarray(buf)
            lengths = None
        validity = None
        if c.validity is not None:
            v = np.zeros(cap, bool)
            v[:n] = c.validity[:n].astype(bool)
            validity = jnp.asarray(v)
        dcols.append(DeviceColumn(f.dtype, data, validity, lengths))
    sel = jnp.arange(cap, dtype=jnp.int32) < n
    return DeviceBatch(schema, tuple(dcols), sel, compacted=True)


class TpuHostShuffleExchangeExec(TpuExec):
    """Shuffle through host files with native tudo serialization.

    ``execute(p)`` yields partition p's rows — identical row order to the
    in-process exchange (the bucket sort is stable and map files read in
    order)."""

    def __init__(self, child: TpuExec, num_partitions: int,
                 keys: Optional[Sequence[Expression]] = None,
                 nthreads: int = 4, min_bucket: int = 1024):
        super().__init__(child.schema, child)
        self.nparts = num_partitions
        self.keys = list(keys) if keys else None
        self.nthreads = nthreads
        self.min_bucket = min_bucket
        self._mat_lock = threading.Lock()
        self._shuffle_id: Optional[int] = None
        self._map_parts: List[int] = []

    def node_string(self):
        kind = "hash" if self.keys else "roundrobin"
        return (f"TpuHostShuffleExchange [{kind} {self.nparts} "
                f"threads={self.nthreads}]")

    def num_partitions(self) -> int:
        return self.nparts

    def _pids(self, b: DeviceBatch) -> jnp.ndarray:
        """Device murmur3 partition ids (hash keys); delegated to the
        same kernel the in-process exchange uses."""
        from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
        return TpuShuffleExchangeExec._pids(self, b, 0)

    def _materialize(self) -> None:
        with self._mat_lock:
            if self._shuffle_id is not None:
                return
            env = ShuffleEnv.get()
            sid = env.new_shuffle_id()
            child = self.children[0]
            row_base = 0
            t0 = time.perf_counter()
            with self.timer("writeTime"):
                for m in range(child.num_partitions()):
                    cancel.check()
                    writer = ShuffleWriter(env, sid, m, self.nparts,
                                           self.nthreads)
                    for b in child.execute(m):
                        live = np.asarray(b.sel)
                        if self.keys:
                            pid = np.asarray(self._pids(b))
                        else:
                            idx = np.cumsum(live) - 1 + row_base
                            pid = (idx % self.nparts).astype(np.int32)
                            row_base += int(live.sum())
                        cols = _host_views(b)
                        written = writer.write_batch(cols, pid, live)
                        self.metric("bytesWritten").add(written)
                    writer.close()
                    self._map_parts.append(m)
            _TM_WRITE_S.inc(time.perf_counter() - t0)
            _TM_EXCHANGES.inc()
            _TM_PARTITIONS.inc(self.nparts)
            self._shuffle_id = sid
            # shuffle files die with the exec (query lifetime)
            weakref.finalize(self, env.remove_shuffle, sid)

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        yield from self.execute_pid_range(partition, partition + 1)

    # -- AQE stats + shaped reads [REF: GpuAQEShuffleReadExec] -----------
    def aqe_partition_stats(self):
        """Per-reduce-partition byte sizes, summed from the shuffle
        files' section tables (no data read)."""
        import os
        import struct
        self._materialize()
        env = ShuffleEnv.get()
        sizes = np.zeros(self.nparts, np.int64)
        for m in self._map_parts:
            path = env.map_file(self._shuffle_id, m)
            with open(path, "rb") as f:
                f.read(8)  # magic + nparts
                while True:
                    tbl = f.read(8 * self.nparts)
                    if not tbl:
                        break
                    rec = np.frombuffer(tbl, np.int64)
                    sizes += rec
                    f.seek(int(rec.sum()), os.SEEK_CUR)
        st = stats.current()
        if st is not None:
            st.record_partitions(self, sizes, unit="bytes")
        return "bytes", sizes

    def _read_concat(self, parts) -> tuple:
        """Reduce-side fetch through the ``shuffle_exchange`` failure
        domain.  Map files are immutable once materialized, so the whole
        read is idempotent and retries simply re-read (bytesRead counts
        every attempt).  Not degradable: exhaustion is a domain-tagged
        terminal error."""
        env = ShuffleEnv.get()
        reader = ShuffleReader(env, self._shuffle_id, self._map_parts,
                               self.schema)
        parts = list(parts)

        def attempt():
            R.INJECTOR.on("shuffle_exchange")
            records = []
            for p in parts:
                cancel.check()
                records.extend(reader.read_partition(p))
            return records

        t0 = time.perf_counter()
        with self.timer("readTime"):
            records = R.run_guarded("shuffle_exchange", attempt,
                                    op="shuffle_read")
        _TM_READ_S.inc(time.perf_counter() - t0)
        return _concat_views(self.schema, records)

    def execute_pid_range(self, lo: int, hi: int
                          ) -> Iterator[DeviceBatch]:
        self._materialize()
        n, cols = self._read_concat(range(lo, hi))
        if n == 0:
            return
        with self.timer("transferTime"):
            out = _to_device(self.schema, cols, n, self.min_bucket)
        self.metric("numOutputRows").add(n)
        self.metric("numOutputBatches").add(1)
        yield out

    def execute_split(self, p: int, j: int, k: int
                      ) -> Iterator[DeviceBatch]:
        """Slice j of k of a skewed partition: host-side interleaved row
        slice before the H2D (same rank rule as the device exchange)."""
        self._materialize()
        n, cols = self._read_concat([p])
        if n == 0:
            return
        idx = np.arange(j, n, k)
        sliced = []
        for c in cols:
            data = c.data[:n][idx]
            validity = (None if c.validity is None
                        else c.validity[:n][idx])
            lengths = (None if c.lengths is None
                       else c.lengths[:n][idx])
            sliced.append(HostColView(c.dtype, data, validity, lengths))
        m = len(idx)
        if m == 0:
            return
        with self.timer("transferTime"):
            out = _to_device(self.schema, sliced, m, self.min_bucket)
        self.metric("numOutputRows").add(m)
        self.metric("numOutputBatches").add(1)
        yield out
