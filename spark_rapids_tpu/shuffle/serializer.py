"""tudo shuffle serialization — Python binding + zero-copy deserializer.

[REF: spark-rapids-jni :: kudo/KudoSerializer, sql-plugin ::
 GpuColumnarBatchSerializer.scala :: SerializedTableColumn]

The write side is native C++ (native/tudo.cpp): one pass buckets rows by
partition id (counting sort), a second threaded pass gather-serializes
each partition into one contiguous buffer.  The wire layout keeps every
column section a contiguous dtype run, so the read side is pure numpy
``frombuffer`` views — no native code and no copies until the H2D pad.

A pure-numpy fallback serializer covers toolchain-less hosts (flagged by
``native_enabled()``); format-identical, so readers never care.
"""

from __future__ import annotations

import ctypes
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as T

_MAGIC = 0x30445554  # "TUD0"


class HostColView:
    """One column of a host-side batch, C-layout, ready to serialize.

    ``data``: fixed width → 1-D array; string → 2-D uint8 matrix.
    """

    __slots__ = ("dtype", "data", "validity", "lengths")

    def __init__(self, dtype: T.DataType, data: np.ndarray,
                 validity: Optional[np.ndarray],
                 lengths: Optional[np.ndarray]):
        self.dtype = dtype
        self.data = np.ascontiguousarray(data)
        self.validity = (None if validity is None
                         else np.ascontiguousarray(
                             validity.astype(np.uint8, copy=False)))
        self.lengths = (None if lengths is None
                        else np.ascontiguousarray(
                            lengths.astype(np.int32, copy=False)))

    @property
    def is_string(self) -> bool:
        return self.lengths is not None


class _ColDesc(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p),
                ("validity", ctypes.c_void_p),
                ("lengths", ctypes.c_void_p),
                ("kind", ctypes.c_int32),
                ("itemsize", ctypes.c_int32)]


_lib = None
_lib_tried = False


def _tudo_lib():
    global _lib, _lib_tried
    if not _lib_tried:
        from spark_rapids_tpu.native import load_library
        _lib = load_library("tudo")
        _lib_tried = True
        if _lib is not None:
            _lib.tudo_bucket_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p]
            _lib.tudo_partition_sizes.argtypes = [
                ctypes.c_int, ctypes.POINTER(_ColDesc), ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p]
            _lib.tudo_partition_write.argtypes = [
                ctypes.c_int, ctypes.POINTER(_ColDesc), ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32]
            if hasattr(_lib, "tudo_scatter_sizes"):
                _lib.tudo_scatter_sizes.argtypes = [
                    ctypes.c_int, ctypes.POINTER(_ColDesc),
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                    ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p]
                _lib.tudo_scatter_write.argtypes = [
                    ctypes.c_int, ctypes.POINTER(_ColDesc),
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                    ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p]
    return _lib


def native_enabled() -> bool:
    return _tudo_lib() is not None


def _ptr(a: Optional[np.ndarray]):
    return None if a is None else a.ctypes.data_as(ctypes.c_void_p)


def _descs(cols: Sequence[HostColView]):
    arr = (_ColDesc * len(cols))()
    keepalive = []
    for i, c in enumerate(cols):
        if c.is_string:
            kind, isz = 1, int(c.data.shape[1]) if c.data.ndim == 2 else 1
        else:
            # isz = bytes per ROW: decimal128 rides as int64[n, 2]
            kind = 0
            isz = int(c.data.dtype.itemsize) * (
                int(c.data.shape[1]) if c.data.ndim == 2 else 1)
        arr[i] = _ColDesc(
            c.data.ctypes.data, None if c.validity is None
            else c.validity.ctypes.data,
            None if c.lengths is None else c.lengths.ctypes.data,
            kind, isz)
        keepalive.append(c)
    return arr, keepalive


import threading as _threading

_scratch_tls = _threading.local()


def _scratch_buf(nbytes: int) -> np.ndarray:
    """Thread-local grow-only output buffer.  np.empty pays soft page
    faults on every first touch — measured 80-160 ms for a 64 MB
    serialize on the single-core shuffle hosts, 4-6x the actual scatter
    time; steady-state writers serialize into warm pages instead."""
    buf = getattr(_scratch_tls, "buf", None)
    if buf is None or buf.size < nbytes:
        buf = np.empty(max(nbytes, 1 << 20), np.uint8)
        buf[::4096] = 0  # touch pages now, off the steady-state path
        _scratch_tls.buf = buf
    return buf


def serialize_partitions(
    cols: Sequence[HostColView], pids: np.ndarray,
    live: Optional[np.ndarray], nparts: int, nthreads: int = 4,
    scratch: bool = False,
) -> List[memoryview]:
    """Bucket rows by pid and serialize each partition: one tudo buffer
    per partition (dead rows dropped).

    ``scratch=True`` serializes into a THREAD-LOCAL reusable buffer: the
    returned memoryviews alias it and are only valid until this thread's
    next scratch call — for callers (the shuffle file writer) that
    consume the sections before serializing the next batch."""
    n = int(pids.shape[0])
    pids = np.ascontiguousarray(pids.astype(np.int32, copy=False))
    live8 = (None if live is None else
             np.ascontiguousarray(live.astype(np.uint8, copy=False)))
    lib = _tudo_lib()
    if lib is None:
        return _py_serialize_partitions(cols, pids, live8, nparts)
    descs, keep = _descs(cols)
    sizes = np.empty(nparts, np.int64)
    import os
    effective_threads = min(int(nthreads), os.cpu_count() or 1)
    if hasattr(lib, "tudo_scatter_write") and effective_threads <= 1:
        # streaming scatter: sequential source reads + one write cursor
        # per partition — 3-4x the permutation gather on the single-core
        # hosts the shuffle writer runs on (native/tudo.cpp rationale).
        # With >1 EFFECTIVE thread the threaded per-partition gather
        # wins, and spark.rapids.shuffle.multiThreaded.writer.threads
        # stays honored.
        work = np.empty(nparts * (1 + len(cols)), np.int64)
        lib.tudo_scatter_sizes(len(cols), descs, _ptr(pids), _ptr(live8),
                               n, nparts, _ptr(sizes), _ptr(work))
        offsets = np.zeros(nparts, np.int64)
        np.cumsum(sizes[:-1], out=offsets[1:])
        total = int(sizes.sum())
        out = (_scratch_buf(total) if scratch
               else np.empty(total, np.uint8))
        lib.tudo_scatter_write(len(cols), descs, _ptr(pids), _ptr(live8),
                               n, nparts, _ptr(out), _ptr(offsets),
                               _ptr(work))
        mv = memoryview(out)
        return [mv[int(offsets[p]):int(offsets[p] + sizes[p])]
                for p in range(nparts)]
    idx = np.empty(n, np.int32)
    starts = np.empty(nparts + 1, np.int64)
    lib.tudo_bucket_rows(_ptr(pids), _ptr(live8), n, nparts,
                         _ptr(idx), _ptr(starts))
    lib.tudo_partition_sizes(len(cols), descs, _ptr(idx), _ptr(starts),
                             nparts, _ptr(sizes))
    offsets = np.zeros(nparts, np.int64)
    np.cumsum(sizes[:-1], out=offsets[1:])
    out = np.empty(int(sizes.sum()), np.uint8)
    lib.tudo_partition_write(len(cols), descs, _ptr(idx), _ptr(starts),
                             nparts, _ptr(out), _ptr(offsets),
                             int(nthreads))
    mv = memoryview(out)
    return [mv[int(offsets[p]):int(offsets[p] + sizes[p])]
            for p in range(nparts)]


def _py_serialize_partitions(cols, pids, live8, nparts) -> List[memoryview]:
    """Format-identical numpy fallback (no C++ toolchain)."""
    keep = np.ones(pids.shape[0], bool) if live8 is None else live8.astype(
        bool)
    out = []
    for p in range(nparts):
        idx = np.nonzero(keep & (pids == p))[0].astype(np.int32)
        out.append(memoryview(_py_serialize_one(cols, idx)))
    return out


def _py_serialize_one(cols, idx: np.ndarray) -> bytes:
    n = len(idx)
    parts = [struct.pack("<IIqI", _MAGIC, 1, n, len(cols))]
    for c in cols:
        if c.is_string:
            kind, isz = 1, int(c.data.shape[1]) if c.data.ndim == 2 else 1
        else:
            kind = 0
            isz = int(c.data.dtype.itemsize) * (
                int(c.data.shape[1]) if c.data.ndim == 2 else 1)
        parts.append(struct.pack("<BBH", kind, 1 if c.validity is not None
                                 else 0, isz))
    for c in cols:
        if c.is_string:
            lens = c.lengths[idx]
            parts.append(lens.astype(np.int32).tobytes())
            if n:
                mat = c.data[idx]
                ii = np.repeat(np.arange(n), lens)
                jj = (np.arange(int(lens.sum()))
                      - np.repeat(np.cumsum(lens) - lens, lens))
                parts.append(mat[ii, jj].tobytes())
        else:
            parts.append(c.data[idx].tobytes())
        if c.validity is not None:
            parts.append(c.validity[idx].tobytes())
    return b"".join(parts)


def deserialize(buf, schema: T.StructType
                ) -> Tuple[int, List[HostColView]]:
    """Zero-copy numpy views over one tudo buffer → (nrows, columns).

    String sections unpack to a padded byte matrix (vectorized)."""
    b = np.frombuffer(buf, np.uint8)
    magic, ver, nrows, ncols = struct.unpack_from("<IIqI", b, 0)
    assert magic == _MAGIC and ver == 1, "bad tudo buffer"
    assert ncols == len(schema.fields), (ncols, len(schema.fields))
    off = 20
    metas = []
    for _ in range(ncols):
        kind, hasv, isz = struct.unpack_from("<BBH", b, off)
        off += 4
        metas.append((kind, hasv, isz))
    cols: List[HostColView] = []
    for f, (kind, hasv, isz) in zip(schema.fields, metas):
        if kind == 1:
            lengths = np.frombuffer(buf, np.int32, nrows, off)
            off += nrows * 4
            total = int(lengths.sum())
            packed = np.frombuffer(buf, np.uint8, total, off)
            off += total
            width = max(int(lengths.max()) if nrows else 1, 1)
            mat = np.zeros((nrows, width), np.uint8)
            if total:
                ii = np.repeat(np.arange(nrows), lengths)
                jj = (np.arange(total)
                      - np.repeat(np.cumsum(lengths) - lengths, lengths))
                mat[ii, jj] = packed
            data, lens = mat, lengths
        elif (isinstance(f.dtype, T.DecimalType)
              and f.dtype.precision > T.DecimalType.MAX_LONG_DIGITS):
            assert isz == 16, (f.name, isz)
            data = np.frombuffer(buf, np.int64, nrows * 2,
                                 off).reshape(nrows, 2)
            off += nrows * 16
            lens = None
        else:
            npdt = np.dtype(T.to_numpy_dtype(f.dtype))
            assert npdt.itemsize == isz, (f.name, npdt, isz)
            data = np.frombuffer(buf, npdt, nrows, off)
            off += nrows * isz
            lens = None
        validity = None
        if hasv:
            validity = np.frombuffer(buf, np.uint8, nrows, off)
            off += nrows
        cols.append(HostColView(f.dtype, data, validity, lens))
    return nrows, cols
