"""Shuffle subsystem: wire serialization + host-path transport.

[REF: sql-plugin/../rapids/shuffle/, RapidsShuffleInternalManagerBase]
— three transports behind ``spark.rapids.shuffle.mode``:

* ``serializer``  — the tudo columnar wire format (kudo analog): native
  C++ partition-scatter writer, zero-copy numpy reader.
* ``manager``     — shuffle file layout, writer/reader, ShuffleEnv.
* ``exchange``    — TpuHostShuffleExchangeExec, the MULTITHREADED-mode
  physical exec.

The ICI collective transport lives in exec/distributed.py +
parallel/shuffle.py; the CACHE_ONLY in-process exchange in
exec/exchange.py.
"""
