"""MULTITHREADED host-path shuffle: writer, reader, shuffle file layout.

[REF: sql-plugin/../RapidsShuffleInternalManagerBase.scala ::
 RapidsShuffleThreadedWriter/Reader, GpuShuffleEnv] — the reference's
default shuffle mode: serialize device batches on a thread pool into
standard shuffle files, fetch + deserialize on the reduce side.  Here the
map side is one file per map partition:

  [u32 'TUDF'][u32 nparts]
  repeated per input batch:
    [i64 sizes[nparts]]  then the nparts tudo sections back-to-back

A reduce task seeks straight to its section in every map file (offsets
from the per-record size table) — the local-filesystem analog of Spark's
IndexShuffleBlockResolver index.  Serialization rides the native tudo
library threaded by ``spark.rapids.shuffle.multiThreaded.writer.threads``.
"""

from __future__ import annotations

import os
import shutil
import struct
import tempfile
import threading
import uuid
from typing import Iterator, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.runtime import resilience as R
from spark_rapids_tpu.runtime import telemetry as TM
from spark_rapids_tpu.shuffle.serializer import (
    HostColView, deserialize, serialize_partitions)

_FILE_MAGIC = struct.pack("<I", 0x46445554)  # "TUDF"

# process-cumulative mirrors of the per-env metrics dict
_TM_SHUFFLE = {
    "bytesWritten": TM.REGISTRY.counter(
        "tpuq_shuffle_bytes_written_total",
        "host-shuffle bytes serialized to map files"),
    "bytesRead": TM.REGISTRY.counter(
        "tpuq_shuffle_bytes_read_total",
        "host-shuffle bytes fetched by reduce reads"),
}


class ShuffleEnv:
    """Process-wide shuffle workspace [REF: GpuShuffleEnv]."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        self.base_dir = tempfile.mkdtemp(prefix="tpuq-shuffle-")
        self._next_id = 0
        self._metrics_lock = threading.Lock()
        self.metrics = {"bytesWritten": 0, "bytesRead": 0}

    def add_metric(self, name: str, v: int) -> None:
        with self._metrics_lock:
            self.metrics[name] += v
        tm = _TM_SHUFFLE.get(name)
        if tm is not None:
            tm.inc(v)

    @classmethod
    def get(cls) -> "ShuffleEnv":
        with cls._lock:
            if cls._instance is None:
                cls._instance = ShuffleEnv()
            return cls._instance

    def new_shuffle_id(self) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        os.makedirs(self._dir(sid), exist_ok=True)
        return sid

    def _dir(self, shuffle_id: int) -> str:
        return os.path.join(self.base_dir, f"shuffle-{shuffle_id}")

    def map_file(self, shuffle_id: int, map_part: int) -> str:
        return os.path.join(self._dir(shuffle_id), f"map-{map_part}.tudo")

    def remove_shuffle(self, shuffle_id: int) -> None:
        shutil.rmtree(self._dir(shuffle_id), ignore_errors=True)


class ShuffleWriter:
    """Writes one map partition's batches into its shuffle file."""

    def __init__(self, env: ShuffleEnv, shuffle_id: int, map_part: int,
                 nparts: int, nthreads: int):
        self.env = env
        self.path = env.map_file(shuffle_id, map_part)
        self.nparts = nparts
        self.nthreads = nthreads
        self._f = open(self.path, "wb")
        self._f.write(_FILE_MAGIC)
        self._f.write(struct.pack("<I", nparts))

    def write_batch(self, cols: Sequence[HostColView], pids: np.ndarray,
                    live: Optional[np.ndarray]) -> int:
        """Serialize one batch's rows into per-partition sections.

        Serialization passes the ``shuffle_ser`` failure domain: the
        sections are produced (retryably — nothing is written until
        serialization succeeds) before any bytes hit the map file, so a
        retried fault never leaves a partially-written record.  The
        domain is not degradable; exhaustion is a domain-tagged
        terminal error."""
        def attempt():
            R.INJECTOR.on("shuffle_ser")
            # scratch=True: sections are consumed (written to the map
            # file) before this thread serializes its next batch
            return serialize_partitions(cols, pids, live, self.nparts,
                                        self.nthreads, scratch=True)

        sections = R.run_guarded("shuffle_ser", attempt,
                                 op="shuffle_serialize")
        sizes = np.array([len(s) for s in sections], np.int64)
        self._f.write(sizes.tobytes())
        for s in sections:
            self._f.write(s)
        written = int(sizes.sum()) + sizes.nbytes
        self.env.add_metric("bytesWritten", written)
        return written

    def close(self):
        self._f.close()


class ShuffleReader:
    """Reads one reduce partition's sections from every map file."""

    def __init__(self, env: ShuffleEnv, shuffle_id: int,
                 map_parts: Sequence[int], schema: T.StructType):
        self.env = env
        self.shuffle_id = shuffle_id
        self.map_parts = list(map_parts)
        self.schema = schema

    def read_partition(self, p: int) -> Iterator[tuple]:
        """Yields (nrows, host column views) per serialized record."""
        for m in self.map_parts:
            path = self.env.map_file(self.shuffle_id, m)
            with open(path, "rb") as f:
                magic = f.read(4)
                assert magic == _FILE_MAGIC, path
                (nparts,) = struct.unpack("<I", f.read(4))
                while True:
                    size_tbl = f.read(8 * nparts)
                    if not size_tbl:
                        break
                    sizes = np.frombuffer(size_tbl, np.int64)
                    # seek directly to section p, skip the rest
                    f.seek(int(sizes[:p].sum()), os.SEEK_CUR)
                    buf = f.read(int(sizes[p]))
                    self.env.add_metric("bytesRead", len(buf))
                    f.seek(int(sizes[p + 1:].sum()), os.SEEK_CUR)
                    nrows, cols = deserialize(buf, self.schema)
                    if nrows:
                        yield nrows, cols
