"""Minimal Avro object-container codec (pure python, no dependency).

[REF: sql-plugin/../GpuAvroScan.scala — the reference host-parses Avro;
 SURVEY §2.1 #20.  Also the enabling piece for Iceberg (§2.1 #31):
 Iceberg's manifest lists and manifests are Avro files.]

Scope (deliberate): the container format (magic, metadata, sync-marked
blocks, null/deflate codecs) and the binary encoding of records built
from primitives, nullable unions, arrays, maps, enums, fixed — enough
for Iceberg metadata and flat data files.  Schema resolution/evolution
is not implemented (readers use the writer schema embedded in the file).
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, Dict, List, Tuple

MAGIC = b"Obj\x01"


class AvroError(Exception):
    pass


# ---------------------------------------------------------------------------
# binary primitives
# ---------------------------------------------------------------------------

def _read_long(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise AvroError("EOF in varint")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not (v & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # zigzag


def _write_long(out: io.BytesIO, v: int) -> None:
    u = (v << 1) ^ (v >> 63) if v < 0 else (v << 1)
    u &= (1 << 64) - 1
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise AvroError("EOF in bytes")
    return data


def _write_bytes(out: io.BytesIO, b: bytes) -> None:
    _write_long(out, len(b))
    out.write(b)


# ---------------------------------------------------------------------------
# schema-driven decode / encode
# ---------------------------------------------------------------------------

def _norm_schema(schema):
    """Normalize: type names may be bare strings or {"type": ...}."""
    if isinstance(schema, str):
        return {"type": schema}
    return schema


def decode_value(buf: io.BytesIO, schema) -> Any:
    s = _norm_schema(schema)
    t = s["type"] if isinstance(s, dict) else s
    if isinstance(s, list):  # union
        idx = _read_long(buf)
        if not 0 <= idx < len(s):
            raise AvroError(f"union branch {idx} out of range")
        return decode_value(buf, s[idx])
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1)[0] != 0
    if t in ("int", "long"):
        return _read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t in ("bytes",):
        return _read_bytes(buf)
    if t == "string":
        return _read_bytes(buf).decode("utf-8")
    if t == "record":
        return {f["name"]: decode_value(buf, f["type"])
                for f in s["fields"]}
    if t == "array":
        out = []
        while True:
            n = _read_long(buf)
            if n == 0:
                break
            if n < 0:
                _read_long(buf)  # block byte size — skippable
                n = -n
            for _ in range(n):
                out.append(decode_value(buf, s["items"]))
        return out
    if t == "map":
        out = {}
        while True:
            n = _read_long(buf)
            if n == 0:
                break
            if n < 0:
                _read_long(buf)
                n = -n
            for _ in range(n):
                k = _read_bytes(buf).decode("utf-8")
                out[k] = decode_value(buf, s["values"])
        return out
    if t == "enum":
        return s["symbols"][_read_long(buf)]
    if t == "fixed":
        return buf.read(s["size"])
    if isinstance(t, (dict, list)):
        return decode_value(buf, t)
    raise AvroError(f"unsupported avro type {t!r}")


def encode_value(out: io.BytesIO, schema, v: Any) -> None:
    s = _norm_schema(schema)
    t = s["type"] if isinstance(s, dict) else s
    if isinstance(s, list):  # union: first matching branch
        for i, branch in enumerate(s):
            bt = _norm_schema(branch)
            bt = bt["type"] if isinstance(bt, dict) else bt
            if (v is None) == (bt == "null"):
                _write_long(out, i)
                encode_value(out, branch, v)
                return
        raise AvroError(f"no union branch for {v!r}")
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if v else b"\x00")
    elif t in ("int", "long"):
        _write_long(out, int(v))
    elif t == "float":
        out.write(struct.pack("<f", v))
    elif t == "double":
        out.write(struct.pack("<d", v))
    elif t == "bytes":
        _write_bytes(out, bytes(v))
    elif t == "string":
        _write_bytes(out, str(v).encode("utf-8"))
    elif t == "record":
        for f in s["fields"]:
            encode_value(out, f["type"], v.get(f["name"]))
    elif t == "array":
        if v:
            _write_long(out, len(v))
            for item in v:
                encode_value(out, s["items"], item)
        _write_long(out, 0)
    elif t == "map":
        if v:
            _write_long(out, len(v))
            for k, mv in v.items():
                _write_bytes(out, str(k).encode())
                encode_value(out, s["values"], mv)
        _write_long(out, 0)
    elif t == "enum":
        _write_long(out, s["symbols"].index(v))
    elif t == "fixed":
        out.write(bytes(v))
    elif isinstance(t, (dict, list)):
        encode_value(out, t, v)
    else:
        raise AvroError(f"unsupported avro type {t!r}")


# ---------------------------------------------------------------------------
# container file
# ---------------------------------------------------------------------------

def read_container(path: str) -> Tuple[dict, List[dict]]:
    """Avro object-container file → (writer schema, list of records)."""
    with open(path, "rb") as f:
        raw = f.read()
    buf = io.BytesIO(raw)
    if buf.read(4) != MAGIC:
        raise AvroError(f"not an avro container: {path}")
    meta = decode_value(buf, {"type": "map", "values": "bytes"})
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise AvroError(f"unsupported avro codec {codec!r}")
    sync = buf.read(16)
    records: List[dict] = []
    while buf.tell() < len(raw):
        n = _read_long(buf)
        size = _read_long(buf)
        block = buf.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        bbuf = io.BytesIO(block)
        for _ in range(n):
            records.append(decode_value(bbuf, schema))
        if buf.read(16) != sync:
            raise AvroError("sync marker mismatch")
    return schema, records


def write_container(path: str, schema: dict, records: List[dict],
                    codec: str = "null") -> None:
    import os
    body = io.BytesIO()
    for r in records:
        encode_value(body, schema, r)
    block = body.getvalue()
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        block = comp.compress(block) + comp.flush()
    sync = os.urandom(16)
    out = io.BytesIO()
    out.write(MAGIC)
    encode_value(out, {"type": "map", "values": "bytes"},
                 {"avro.schema": json.dumps(schema).encode(),
                  "avro.codec": codec.encode()})
    out.write(sync)
    _write_long(out, len(records))
    _write_long(out, len(block))
    out.write(block)
    out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())


# ---------------------------------------------------------------------------
# flat records → arrow (the read.avro data path)
# ---------------------------------------------------------------------------

_AVRO_TO_ARROW = {"boolean": "bool", "int": "int32", "long": "int64",
                  "float": "float32", "double": "float64",
                  "string": "string", "bytes": "binary"}


def avro_to_arrow(path: str):
    """Flat-record avro file → pyarrow.Table (primitive/nullable-union
    fields; logical types date/timestamp-micros honored)."""
    import pyarrow as pa
    schema, records = read_container(path)
    if _norm_schema(schema).get("type") != "record":
        raise AvroError("read.avro expects a record schema")
    fields = []
    for f in _norm_schema(schema)["fields"]:
        ft = f["type"]
        if isinstance(ft, list):  # nullable union
            non_null = [b for b in ft if _norm_schema(b).get(
                "type", b) != "null"]
            if len(non_null) != 1:
                raise AvroError(
                    f"field {f['name']}: only [null, T] unions supported")
            ft = non_null[0]
        ft = _norm_schema(ft)
        t = ft.get("type")
        logical = ft.get("logicalType")
        if logical == "date":
            at = pa.date32()
        elif logical == "timestamp-micros":
            at = pa.timestamp("us", tz="UTC")
        elif t in _AVRO_TO_ARROW:
            at = getattr(pa, _AVRO_TO_ARROW[t])()
        else:
            raise AvroError(
                f"field {f['name']}: avro type {t!r} not supported in "
                "read.avro (flat primitives only)")
        fields.append((f["name"], at))
    arrays = []
    for name, at in fields:
        vals = [r.get(name) for r in records]
        if pa.types.is_date32(at):
            import datetime
            vals = [None if v is None
                    else datetime.date(1970, 1, 1)
                    + datetime.timedelta(days=v) for v in vals]
        elif pa.types.is_timestamp(at):
            arrays.append(pa.array(
                [None if v is None else int(v) for v in vals],
                type=pa.int64()).cast(at))
            continue
        arrays.append(pa.array(vals, type=at))
    return pa.table(arrays, names=[n for n, _ in fields])
