"""Delta deletion-vector (DV) decoding — row-level deletes at read.

[REF: delta-io PROTOCOL.md "Deletion Vectors" + delta-storage
 RoaringBitmapArray; spark-rapids GpuDeltaParquetFileFormat applies the
 same vectors as a row mask during the parquet decode]

A DV marks deleted row positions of ONE data file as a 64-bit roaring
bitmap ("RoaringBitmapArray"): the 64-bit position space is split into
2^32 buckets by the high 32 bits; each non-empty bucket holds a standard
32-bit Roaring bitmap of the low bits.  Wire layout implemented here:

* descriptor (in the `add` action): ``storageType`` 'i' (inline),
  'u' (relative file, name derived from a z85-encoded UUID) or
  'p' (absolute path); ``pathOrInlineDv``; ``offset`` (file storage);
  ``sizeInBytes``; ``cardinality``.
* serialized blob: int32 LE magic 1681511377, then int64 LE bucket
  count, then per bucket: int32 LE high-key + a standard
  `Roaring format spec <https://github.com/RoaringBitmap/RoaringFormatSpec>`_
  32-bit bitmap (cookies 12346/12347, array/bitmap/run containers).
* file storage: 1 version byte (=1) at offset 0; each blob at its
  descriptor ``offset`` as int32 BE length, blob bytes, int32 BE CRC32
  (Java DataOutputStream framing around a little-endian payload).

The synthesized-fixture tests mirror this writer-side; real tables
produced by Delta should decode identically — any divergence fails
loudly (magic/cookie checks), never silently drops deletes.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import List, Optional

import numpy as np

MAGIC = 1681511377
SERIAL_COOKIE_NO_RUN = 12346
SERIAL_COOKIE = 12347
NO_OFFSET_THRESHOLD = 4

_Z85_CHARS = ("0123456789abcdefghijklmnopqrstuvwxyz"
              "ABCDEFGHIJKLMNOPQRSTUVWXYZ.-:+=^!/*?&<>()[]{}@%$#")
_Z85_MAP = {c: i for i, c in enumerate(_Z85_CHARS)}


def z85_decode(s: str) -> bytes:
    """ZeroMQ base85 (the encoding Delta uses for DV file UUIDs)."""
    if len(s) % 5:
        raise ValueError(f"z85 length {len(s)} not a multiple of 5")
    out = bytearray()
    for i in range(0, len(s), 5):
        v = 0
        for c in s[i:i + 5]:
            v = v * 85 + _Z85_MAP[c]
        out += v.to_bytes(4, "big")
    return bytes(out)


def z85_encode(b: bytes) -> str:
    if len(b) % 4:
        raise ValueError(f"z85 input length {len(b)} not a multiple of 4")
    out = []
    for i in range(0, len(b), 4):
        v = int.from_bytes(b[i:i + 4], "big")
        chunk = []
        for _ in range(5):
            v, r = divmod(v, 85)
            chunk.append(_Z85_CHARS[r])
        out.extend(reversed(chunk))
    return "".join(out)


def _parse_roaring32(buf: memoryview, off: int):
    """One standard-format 32-bit Roaring bitmap → (uint32 values, end
    offset)."""
    cookie = struct.unpack_from("<i", buf, off)[0]
    has_runs = (cookie & 0xFFFF) == SERIAL_COOKIE
    if has_runs:
        n = (cookie >> 16) + 1
        off += 4
        run_flags = bytes(buf[off:off + (n + 7) // 8])
        off += (n + 7) // 8
    elif cookie == SERIAL_COOKIE_NO_RUN:
        n = struct.unpack_from("<i", buf, off + 4)[0]
        off += 8
        run_flags = b"\x00" * ((n + 7) // 8)
    else:
        raise ValueError(f"bad roaring cookie {cookie}")
    keys = np.zeros(n, np.uint32)
    cards = np.zeros(n, np.int64)
    for i in range(n):
        k, c = struct.unpack_from("<HH", buf, off)
        keys[i], cards[i] = k, c + 1
        off += 4
    if (not has_runs) or n >= NO_OFFSET_THRESHOLD:
        off += 4 * n  # container offsets — sequential parse ignores them
    parts: List[np.ndarray] = []
    for i in range(n):
        is_run = bool(run_flags[i // 8] & (1 << (i % 8)))
        base = np.uint32(keys[i]) << np.uint32(16)
        if is_run:
            n_runs = struct.unpack_from("<H", buf, off)[0]
            off += 2
            vals = []
            for _ in range(n_runs):
                start, length = struct.unpack_from("<HH", buf, off)
                off += 4
                vals.append(np.arange(start, start + length + 1,
                                      dtype=np.uint32))
            lo = (np.concatenate(vals) if vals
                  else np.zeros(0, np.uint32))
        elif cards[i] > 4096:
            # bitmap container: 8 KiB bitset
            words = np.frombuffer(buf, np.uint8, 8192, off)
            off += 8192
            bits = np.unpackbits(words, bitorder="little")
            lo = np.nonzero(bits)[0].astype(np.uint32)
        else:
            lo = np.frombuffer(buf, np.uint16, int(cards[i]),
                               off).astype(np.uint32)
            off += 2 * int(cards[i])
        parts.append(base | lo)
    vals = (np.concatenate(parts) if parts else np.zeros(0, np.uint32))
    return vals, off


def parse_bitmap_array(blob: bytes) -> np.ndarray:
    """Serialized RoaringBitmapArray → sorted int64 positions."""
    buf = memoryview(blob)
    magic = struct.unpack_from("<i", buf, 0)[0]
    if magic != MAGIC:
        raise ValueError(f"bad deletion-vector magic {magic}")
    nbuckets = struct.unpack_from("<q", buf, 4)[0]
    off = 12
    out: List[np.ndarray] = []
    for _ in range(nbuckets):
        high = struct.unpack_from("<i", buf, off)[0]
        off += 4
        lows, off = _parse_roaring32(buf, off)
        out.append((np.int64(high) << np.int64(32))
                   | lows.astype(np.int64))
    if not out:
        return np.zeros(0, np.int64)
    return np.sort(np.concatenate(out))


def dv_file_name(table_path: str, path_or_inline: str) -> str:
    """'u' storage: pathOrInlineDv = <raw random prefix chars> + the
    20-char z85 encoding of the 16-byte UUID (delta-spark splits with
    dropRight(20)/takeRight(20) — the PREFIX is raw text, only the UUID
    is encoded); file = <prefix>/deletion_vector_<uuid>.bin."""
    import uuid as _uuid
    if len(path_or_inline) < 20:
        raise ValueError(
            f"deletion vector path {path_or_inline!r} shorter than a "
            "z85 UUID")
    prefix = path_or_inline[:-20]
    uid = z85_decode(path_or_inline[-20:])
    name = f"deletion_vector_{_uuid.UUID(bytes=uid)}.bin"
    if prefix:
        return os.path.join(table_path, prefix, name)
    return os.path.join(table_path, name)


def read_dv(descriptor: dict, table_path: str) -> np.ndarray:
    """DV descriptor (the `add` action's ``deletionVector``) → sorted
    int64 deleted positions."""
    st = descriptor.get("storageType")
    pod = descriptor["pathOrInlineDv"]
    if st == "i":
        blob = z85_decode(pod)
        size = int(descriptor.get("sizeInBytes", 0) or 0)
        if size:
            blob = blob[:size]  # z85 pads to 4-byte groups
        return parse_bitmap_array(blob)
    if st == "u":
        path = dv_file_name(table_path, pod)
    elif st == "p":
        path = pod
    else:
        raise ValueError(f"unknown DV storage type {st!r}")
    offset = int(descriptor.get("offset", 0) or 0)
    with open(path, "rb") as f:
        f.seek(offset)
        (size,) = struct.unpack(">i", f.read(4))
        blob = f.read(size)
        (crc,) = struct.unpack(">I", f.read(4))
    if (zlib.crc32(blob) & 0xFFFFFFFF) != crc:
        raise ValueError(f"deletion vector checksum mismatch in {path}")
    return parse_bitmap_array(blob)


# ---------------------------------------------------------------------------
# writer side — used by tests to synthesize fixtures (and by any future
# delete/update write path); format-mirror of the parser above
# ---------------------------------------------------------------------------

def serialize_bitmap_array(positions) -> bytes:
    positions = np.asarray(sorted(set(int(p) for p in positions)),
                           np.int64)
    out = bytearray(struct.pack("<i", MAGIC))
    highs = positions >> np.int64(32)
    out += struct.pack("<q", len(np.unique(highs)) if len(positions)
                       else 0)
    for h in np.unique(highs):
        lows = (positions[highs == h] & np.int64(0xFFFFFFFF)).astype(
            np.uint32)
        out += struct.pack("<i", int(h))
        out += _serialize_roaring32(lows)
    return bytes(out)


def _serialize_roaring32(vals: np.ndarray) -> bytes:
    keys = np.unique(vals >> np.uint32(16))
    n = len(keys)
    out = bytearray(struct.pack("<ii", SERIAL_COOKIE_NO_RUN, n))
    conts = []
    for k in keys:
        lo = (vals[(vals >> np.uint32(16)) == k]
              & np.uint32(0xFFFF)).astype(np.uint16)
        out += struct.pack("<HH", int(k), len(lo) - 1)
        if len(lo) > 4096:
            bits = np.zeros(65536, np.uint8)
            bits[lo] = 1
            conts.append(np.packbits(bits, bitorder="little").tobytes())
        else:
            conts.append(lo.tobytes())
    off = len(out) + 4 * n
    for c in conts:
        out += struct.pack("<i", off)
        off += len(c)
    for c in conts:
        out += c
    return bytes(out)


def write_dv_file(path: str, positions) -> dict:
    """Write a single-DV file; returns the descriptor dict for the
    `add` action (absolute-path storage)."""
    blob = serialize_bitmap_array(positions)
    with open(path, "wb") as f:
        f.write(b"\x01")  # format version
        offset = f.tell()
        f.write(struct.pack(">i", len(blob)))
        f.write(blob)
        f.write(struct.pack(">I", zlib.crc32(blob) & 0xFFFFFFFF))
    return {"storageType": "p", "pathOrInlineDv": path,
            "offset": offset, "sizeInBytes": len(blob),
            "cardinality": len(set(int(p) for p in positions))}
