"""Parquet scan + write execs.

[REF: sql-plugin/../GpuParquetScan.scala :: GpuParquetMultiFilePartitionReader
 (MULTITHREADED / COALESCING / PERFILE), GpuParquetFileFormat (write)] —
the reference decodes Parquet pages on GPU via libcudf; a TPU has no
decompression engine (SURVEY §2.2 N6), so phase-1 keeps decode on host
(pyarrow's C++ reader) and lands device-resident batches:

* MULTITHREADED analog: a thread pool reads+decodes files concurrently
  while the device consumes earlier batches (read-ahead overlap);
* COALESCING analog: small files concatenate into one batch up to the
  target batch size before H2D;
* predicate/column pushdown: row-group pruning via pyarrow filters and
  column projection (wired by the planner's pushdown pass when present).
"""

from __future__ import annotations

import concurrent.futures as cf
from typing import Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar import host as H
from spark_rapids_tpu.columnar.column import DeviceBatch, host_to_device
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec.base import CpuExec, TpuExec


def parquet_schema(paths: Sequence[str]) -> T.StructType:
    s = pq.read_schema(paths[0])
    return T.StructType(tuple(
        T.StructField(f.name, T.from_arrow(f.type)) for f in s))


def _partition_files(paths: Sequence[str], num_partitions: int
                     ) -> List[List[str]]:
    parts: List[List[str]] = [[] for _ in range(num_partitions)]
    for i, p in enumerate(sorted(paths)):
        parts[i % num_partitions].append(p)
    return parts


class CpuParquetScanExec(CpuExec):
    def __init__(self, paths: Sequence[str], schema: T.StructType,
                 conf: RapidsConf, columns: Optional[List[str]] = None):
        super().__init__(schema)
        self.paths = list(paths)
        self.conf = conf
        self.columns = columns
        self._num_partitions = max(1, min(len(self.paths),
                                          conf.shuffle_partitions))

    def node_string(self):
        return f"ParquetScan [{len(self.paths)} files]"

    def num_partitions(self) -> int:
        return self._num_partitions

    def execute(self, partition: int) -> Iterator[H.HostBatch]:
        files = _partition_files(self.paths, self._num_partitions)[partition]
        for f in files:
            with self.timer():
                tbl = pq.read_table(f, columns=self.columns)
                b = H.from_arrow_table(tbl)
                b = H.HostBatch(self.schema, b.columns)
            self.metric("numOutputRows").add(b.num_rows)
            self.metric("numOutputBatches").add(1)
            yield b


class TpuParquetScanExec(TpuExec):
    """Multithreaded host decode + H2D — the MULTITHREADED reader analog.

    [REF: GpuMultiFileReader.scala :: MultiFileCloudPartitionReader]
    """

    def __init__(self, paths: Sequence[str], schema: T.StructType,
                 conf: RapidsConf, columns: Optional[List[str]] = None):
        super().__init__(schema)
        self.paths = list(paths)
        self.conf = conf
        self.columns = columns
        self._num_partitions = max(1, min(len(self.paths),
                                          conf.shuffle_partitions))
        self.num_threads = int(conf.get_raw(
            "spark.rapids.sql.multiThreadedRead.numThreads", 4) or 4)

    def node_string(self):
        return f"TpuParquetScan [{len(self.paths)} files]"

    def num_partitions(self) -> int:
        return self._num_partitions

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        files = _partition_files(self.paths, self._num_partitions)[partition]
        if not files:
            return
        with cf.ThreadPoolExecutor(max_workers=self.num_threads) as pool:
            futures = [pool.submit(pq.read_table, f, columns=self.columns)
                       for f in files]
            for fut in futures:
                with self.timer("scanTime"):
                    tbl = fut.result()
                with self.timer():
                    b = host_to_device(tbl)
                    b = DeviceBatch(self.schema, b.columns, b.sel)
                self.metric("numOutputRows").add(
                    int(np.sum(np.asarray(b.sel))))
                self.metric("numOutputBatches").add(1)
                yield b


def _tag_parquet(meta):
    pass


def _convert_parquet(cpu: CpuParquetScanExec, ch, conf):
    return TpuParquetScanExec(cpu.paths, cpu.schema, cpu.conf, cpu.columns)


def write_parquet(table: pa.Table, path: str, mode: str = "error"):
    import os
    if os.path.exists(path):
        if mode in ("error", "errorifexists"):
            raise FileExistsError(path)
        if mode == "ignore":
            return
        if mode == "overwrite":
            import shutil
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.remove(path)
    os.makedirs(path, exist_ok=True)
    pq.write_table(table, os.path.join(path, "part-00000.parquet"))
