"""File-source scan + write execs (parquet, orc).

[REF: sql-plugin/../GpuParquetScan.scala :: GpuParquetMultiFilePartitionReader
 (MULTITHREADED / COALESCING / PERFILE), GpuParquetFileFormat (write),
 GpuOrcScan.scala, GpuFileSourceScanExec.scala (partition values,
 input_file_name), GpuFileFormatDataWriter.scala (dynamic partitions)] —
the reference decodes Parquet pages on GPU via libcudf; a TPU has no
decompression engine (SURVEY §2.2 N6), so phase-1 keeps decode on host
(pyarrow's C++ readers) and lands device-resident batches:

* MULTITHREADED analog: a thread pool reads+decodes files concurrently
  while the device consumes earlier batches (read-ahead overlap);
* predicate pushdown: row-group pruning against parquet column-chunk
  min/max statistics (``prunedRowGroups`` metric); the Filter node above
  re-applies the exact predicate, so pruning only ever has to be
  conservative;
* column pruning: the optimizer narrows the read set to referenced
  columns (plan/optimizer.py);
* hive-style partition values and input_file_name() are appended as
  constant columns per file before H2D.
"""

from __future__ import annotations

import concurrent.futures as cf
from typing import Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar import host as H
from spark_rapids_tpu.columnar.column import DeviceBatch, host_to_device
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec.base import CpuExec, TpuExec


def parquet_schema(paths: Sequence[str]) -> T.StructType:
    s = pq.read_schema(paths[0])
    return T.StructType(tuple(
        T.StructField(f.name, T.from_arrow(f.type)) for f in s))


def orc_schema(paths: Sequence[str]) -> T.StructType:
    import pyarrow.orc as po
    s = po.ORCFile(paths[0]).schema
    return T.StructType(tuple(
        T.StructField(f.name, T.from_arrow(f.type)) for f in s))


def _partition_files(n_files: int, num_partitions: int) -> List[List[int]]:
    parts: List[List[int]] = [[] for _ in range(num_partitions)]
    for i in range(n_files):
        parts[i % num_partitions].append(i)
    return parts


def _rg_may_match(md_rg, colmap, filters) -> bool:
    """Conservative row-group keep test against chunk min/max stats.

    A conjunct that provably matches no non-null value lets the group be
    skipped: predicate comparisons drop null rows anyway, so null-only
    remains never survive the exact Filter above."""
    for name, op, val in filters:
        ci = colmap.get(name)
        if ci is None:
            continue
        st = md_rg.column(ci).statistics
        if st is None or not st.has_min_max:
            continue
        mn, mx = st.min, st.max
        try:
            if op == "eq" and (val < mn or val > mx):
                return False
            if op == "lt" and not (mn < val):
                return False
            if op == "le" and not (mn <= val):
                return False
            if op == "gt" and not (mx > val):
                return False
            if op == "ge" and not (mx >= val):
                return False
        except TypeError:
            continue  # incomparable stats type — keep the group
    return True


class CpuParquetScanExec(CpuExec):
    """Generic file scan (parquet/orc) — CPU oracle path."""

    def __init__(self, relation, conf: RapidsConf):
        super().__init__(relation.schema)
        self.relation = relation
        self.paths = list(relation.paths)
        self.conf = conf
        self.columns = relation.columns
        self._num_partitions = max(1, min(len(self.paths),
                                          conf.shuffle_partitions))
        self._dpp_keep_cache = None
        self._dpp_lock = __import__("threading").Lock()

    def _dpp_keep(self):
        """File indices surviving dynamic partition pruning (None = all).

        Evaluates the build-side subquery ONCE, host-side, before the
        scan pumps [REF: GpuSubqueryBroadcastExec — the reference reuses
        the broadcast; dims are small, so a host evaluation is the
        in-process analog]."""
        if self.relation.dpp is None:
            return None
        with self._dpp_lock:
            if self._dpp_keep_cache is not None:
                return self._dpp_keep_cache
            sub_plan, col_name = self.relation.dpp
            from spark_rapids_tpu.plan.planner import plan_physical
            sub = plan_physical(sub_plan, self.conf)
            values = set()
            for p in range(sub.num_partitions()):
                for b in sub.execute(p):
                    c = b.columns[0]
                    tbl_col = H.to_arrow_column(c)
                    values.update(v for v in tbl_col.to_pylist()
                                  if v is not None)
            keep = {fi for fi, pv in
                    enumerate(self.relation.partition_values)
                    if pv.get(col_name) in values}
            self.metric("dppPrunedFiles").add(len(self.paths) - len(keep))
            self._dpp_keep_cache = keep
            return keep

    def node_string(self):
        extra = ""
        if self.relation.filters:
            extra = f", pushdown={self.relation.filters}"
        return (f"{self.relation.format.capitalize()}Scan "
                f"[{len(self.paths)} files{extra}]")

    def num_partitions(self) -> int:
        return self._num_partitions

    def _data_columns(self) -> Optional[List[str]]:
        if self.columns is not None:
            return self.columns
        np_ = len(self.relation.partition_fields)
        nf = 1 if self.relation.file_name_col else 0
        fields = self.schema.fields
        end = len(fields) - np_ - nf
        return [f.name for f in fields[:end]]

    def _read_file(self, fi, dict_strings=False) -> pa.Table:
        """Read one file's pruned columns + append partition/file cols.

        Columns missing from a file (schema evolution: added after the
        file was written) materialize as nulls — Delta/Spark semantics."""
        path = self.paths[fi]
        cols = self._data_columns()
        by_name = {f.name: f for f in self.schema.fields}
        dels = (self.relation.deletes[fi]
                if self.relation.deletes is not None else None)
        positions = None  # file-absolute row positions of the read rows
        if self.relation.format == "orc":
            import pyarrow.orc as po
            orc = po.ORCFile(path)
            present = set(orc.schema.names)
            read_cols = [c for c in cols if c in present]
            tbl = orc.read(columns=read_cols)
        else:
            read_dict = None
            if dict_strings:
                read_dict = [c for c in cols
                             if isinstance(by_name[c].dtype,
                                           (T.StringType, T.BinaryType))]
            pf = pq.ParquetFile(path, read_dictionary=read_dict)
            present = set(pf.schema_arrow.names)
            read_cols = [c for c in cols if c in present]
            filters = self.relation.filters
            if filters:
                colmap = {pf.metadata.schema.column(i).name: i
                          for i in range(pf.metadata.num_columns)}
                keep = [rg for rg in range(pf.metadata.num_row_groups)
                        if _rg_may_match(pf.metadata.row_group(rg),
                                         colmap, filters)]
                self.metric("prunedRowGroups").add(
                    pf.metadata.num_row_groups - len(keep))
                tbl = (pf.read_row_groups(keep, columns=read_cols)
                       if keep
                       else pf.schema_arrow.empty_table().select(
                           read_cols))
                if dels is not None and len(dels) and keep:
                    # delete positions are FILE-absolute; row-group
                    # pruning shifted local indexes, so rebuild them
                    rg_rows = [pf.metadata.row_group(i).num_rows
                               for i in range(pf.metadata.num_row_groups)]
                    starts = np.concatenate(
                        [[0], np.cumsum(rg_rows)[:-1]])
                    positions = np.concatenate(
                        [np.arange(starts[rg], starts[rg] + rg_rows[rg],
                                   dtype=np.int64) for rg in keep])
            else:
                tbl = pf.read(columns=read_cols)  # reuse the open file
        if dels is not None and len(dels) and tbl.num_rows:
            # row mask from the deleted positions (sorted searchsorted
            # membership — dels can be large, positions larger)
            if positions is None:
                positions = np.arange(tbl.num_rows, dtype=np.int64)
            ix = np.searchsorted(dels, positions)
            hit = np.zeros(len(positions), bool)
            in_rng = ix < len(dels)
            hit[in_rng] = dels[ix[in_rng]] == positions[in_rng]
            self.metric("deletedRows").add(int(hit.sum()))
            tbl = tbl.filter(pa.array(~hit))
        if len(read_cols) < len(cols):
            for c in cols:
                if c not in present:
                    tbl = tbl.append_column(
                        c, pa.nulls(tbl.num_rows,
                                    type=T.to_arrow(by_name[c].dtype)))
            tbl = tbl.select(cols)
        n = tbl.num_rows
        if self.relation.partition_values is not None:
            pv = self.relation.partition_values[fi]
            for f in self.relation.partition_fields:
                v = pv.get(f.name)
                arr = pa.array(
                    [v] * n if v is not None else [None] * n,
                    type=T.to_arrow(f.dtype))
                tbl = tbl.append_column(f.name, arr)
        if self.relation.file_name_col:
            tbl = tbl.append_column(
                "input_file_name()",
                pa.array([path] * n, type=pa.string()))
        return tbl

    def execute(self, partition: int) -> Iterator[H.HostBatch]:
        idxs = _partition_files(len(self.paths),
                                self._num_partitions)[partition]
        keep = self._dpp_keep()
        if keep is not None:
            idxs = [fi for fi in idxs if fi in keep]
        for fi in idxs:
            with self.timer():
                tbl = self._read_file(fi)
                b = H.from_arrow_table(tbl)
                b = H.HostBatch(self.schema, b.columns)
            self.metric("numOutputRows").add(b.num_rows)
            self.metric("numOutputBatches").add(1)
            yield b


class TpuParquetScanExec(TpuExec):
    """Multithreaded host decode + H2D — the MULTITHREADED reader analog.

    [REF: GpuMultiFileReader.scala :: MultiFileCloudPartitionReader]
    """

    def __init__(self, cpu: CpuParquetScanExec):
        super().__init__(cpu.schema)
        self._cpu = cpu
        self.paths = cpu.paths
        self._num_partitions = cpu._num_partitions
        from spark_rapids_tpu import conf as C
        self.num_threads = int(cpu.conf.get(C.MULTITHREADED_READ_THREADS))

    def node_string(self):
        return "Tpu" + self._cpu.node_string()

    def num_partitions(self) -> int:
        return self._num_partitions

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        idxs = _partition_files(len(self.paths),
                                self._num_partitions)[partition]
        keep = self._cpu._dpp_keep()
        if keep is not None:
            idxs = [fi for fi in idxs if fi in keep]
            self.metric("dppPrunedFiles").value = \
                self._cpu.metric("dppPrunedFiles").value
        if not idxs:
            return
        with cf.ThreadPoolExecutor(max_workers=self.num_threads) as pool:
            from spark_rapids_tpu import conf as C
            dict_dec = bool(self._cpu.conf.get(C.PARQUET_DEVICE_DICT))
            futures = [pool.submit(self._cpu._read_file, fi, dict_dec)
                       for fi in idxs]
            for fut in futures:
                with self.timer("scanTime"):
                    tbl = fut.result()
                ndict = sum(1 for c in tbl.columns
                            if pa.types.is_dictionary(c.type))
                if ndict:
                    self.metric("dictDecodedColumns").add(ndict)
                with self.timer():
                    b = host_to_device(tbl)
                    b = DeviceBatch(self.schema, b.columns, b.sel,
                                    compacted=True)
                self.metric("numOutputRows").add(tbl.num_rows)
                self.metric("numOutputBatches").add(1)
                yield b
        # pruning metric accrues on the shared CPU reader
        pruned = self._cpu.metrics.get("prunedRowGroups")
        if pruned is not None:
            self.metric("prunedRowGroups").value = pruned.value


def _tag_parquet(meta):
    pass


def _convert_parquet(cpu: CpuParquetScanExec, ch, conf):
    return TpuParquetScanExec(cpu)


HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


def _prepare_out_dir(path: str, mode: str) -> bool:
    """Returns False when the write should be skipped (mode=ignore)."""
    import os
    if os.path.exists(path):
        if mode in ("error", "errorifexists"):
            raise FileExistsError(path)
        if mode == "ignore":
            return False
        if mode == "overwrite":
            import shutil
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.remove(path)
    os.makedirs(path, exist_ok=True)
    return True


def write_parquet(table: pa.Table, path: str, mode: str = "error",
                  partition_by: Optional[List[str]] = None,
                  fmt: str = "parquet"):
    """Write a table as a directory of part files, optionally
    hive-partitioned [REF: GpuFileFormatDataWriter.scala ::
    GpuDynamicPartitionDataSingleWriter]."""
    import os
    if not _prepare_out_dir(path, mode):
        return

    def _write(tbl: pa.Table, out_dir: str, part_idx: int):
        os.makedirs(out_dir, exist_ok=True)
        fname = f"part-{part_idx:05d}.{fmt}"
        if fmt == "orc":
            import pyarrow.orc as po
            po.write_table(tbl, os.path.join(out_dir, fname))
        else:
            pq.write_table(tbl, os.path.join(out_dir, fname))

    if not partition_by:
        _write(table, path, 0)
        return
    for c in partition_by:
        if c not in table.column_names:
            raise KeyError(f"partitionBy column '{c}' not in output")
    data_cols = [c for c in table.column_names if c not in partition_by]
    # group rows by distinct partition tuple (hash-free: arrow dictionary
    # encode over the tuple string is overkill at host-write volume)
    keys = list(zip(*[table.column(c).to_pylist() for c in partition_by]))
    groups = {}
    for i, k in enumerate(keys):
        groups.setdefault(k, []).append(i)
    for pi, (k, rows) in enumerate(sorted(
            groups.items(), key=lambda kv: str(kv[0]))):
        sub = table.take(pa.array(rows, type=pa.int64())).select(data_cols)
        segs = [f"{c}=" + (HIVE_NULL if v is None else str(v))
                for c, v in zip(partition_by, k)]
        _write(sub, os.path.join(path, *segs), pi)
