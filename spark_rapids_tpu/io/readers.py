"""DataFrameReader / DataFrameWriter — the session.read / df.write API.

[REF: the reference accelerates Spark's DataFrameReader formats via
 GpuReadParquetFileFormat / GpuOrcScan / GpuCSVScan / GpuJsonScan
 (SURVEY §2.1 #19-21); here the host formats are pyarrow's readers and
 the TPU path lands device batches via io/parquet.py et al.]
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson

from spark_rapids_tpu.columnar import dtypes as T


def _expand(path) -> List[str]:
    paths: List[str] = []
    for p in ([path] if isinstance(path, str) else list(path)):
        if os.path.isdir(p):
            paths.extend(sorted(
                f for f in glob.glob(os.path.join(p, "*"))
                if os.path.isfile(f) and not os.path.basename(f).startswith(
                    ("_", "."))))
        else:
            matches = sorted(glob.glob(p))
            paths.extend(matches if matches else [p])
    if not paths:
        raise FileNotFoundError(f"no input files at {path}")
    return paths


class DataFrameReader:
    def __init__(self, session):
        self.session = session
        self._options: Dict[str, str] = {}
        self._schema: Optional[T.StructType] = None

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[str(key)] = value
        return self

    def options(self, **kw) -> "DataFrameReader":
        self._options.update(kw)
        return self

    def schema(self, s: T.StructType) -> "DataFrameReader":
        self._schema = s
        return self

    def parquet(self, path):
        from spark_rapids_tpu.io.parquet import parquet_schema
        from spark_rapids_tpu.plan.logical import ParquetRelation
        from spark_rapids_tpu.sql.dataframe import DataFrame

        paths = _expand(path)
        schema = self._schema or parquet_schema(paths)
        return DataFrame(self.session, ParquetRelation(paths, schema))

    def csv(self, path, header: Optional[bool] = None):
        paths = _expand(path)
        if header is None:
            header = str(self._options.get("header", "false")).lower() in (
                "true", "1")
        read_opts = pacsv.ReadOptions(
            autogenerate_column_names=not header)
        convert = pacsv.ConvertOptions()
        if self._schema is not None:
            # user schema drives column types (and names when headerless)
            if not header:
                read_opts = pacsv.ReadOptions(
                    column_names=self._schema.field_names())
            convert = pacsv.ConvertOptions(column_types={
                f.name: T.to_arrow(f.dtype)
                for f in self._schema.fields})
        tables = [pacsv.read_csv(p, read_options=read_opts,
                                 convert_options=convert) for p in paths]
        tbl = pa.concat_tables(tables, promote_options="permissive")
        if not header and self._schema is None:
            tbl = tbl.rename_columns(
                [f"_c{i}" for i in range(tbl.num_columns)])
        return self.session.createDataFrame(tbl)

    def json(self, path):
        paths = _expand(path)
        parse = pajson.ParseOptions()
        if self._schema is not None:
            parse = pajson.ParseOptions(explicit_schema=pa.schema(
                [(f.name, T.to_arrow(f.dtype))
                 for f in self._schema.fields]))
        tables = [pajson.read_json(p, parse_options=parse) for p in paths]
        tbl = pa.concat_tables(tables, promote_options="permissive")
        return self.session.createDataFrame(tbl)


class DataFrameWriter:
    def __init__(self, df):
        self.df = df
        self._mode = "error"
        self._options: Dict[str, str] = {}

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[str(key)] = value
        return self

    def parquet(self, path: str):
        from spark_rapids_tpu.io.parquet import write_parquet
        write_parquet(self.df.toArrow(), path, self._mode)

    def csv(self, path: str):
        import pyarrow.csv as pacsv
        table = self.df.toArrow()
        if os.path.exists(path) and self._mode == "overwrite":
            import shutil
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path) and self._mode in ("error",
                                                     "errorifexists"):
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        pacsv.write_csv(table, os.path.join(path, "part-00000.csv"))

    def json(self, path: str):
        table = self.df.toArrow()
        if os.path.exists(path) and self._mode == "overwrite":
            import shutil
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path) and self._mode in ("error",
                                                     "errorifexists"):
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        import json as _json
        rows = table.to_pylist()
        with open(os.path.join(path, "part-00000.json"), "w") as f:
            for r in rows:
                f.write(_json.dumps(r, default=str) + "\n")
