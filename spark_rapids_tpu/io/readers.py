"""DataFrameReader / DataFrameWriter — the session.read / df.write API.

[REF: the reference accelerates Spark's DataFrameReader formats via
 GpuReadParquetFileFormat / GpuOrcScan / GpuCSVScan / GpuJsonScan
 (SURVEY §2.1 #19-21); here the host formats are pyarrow's readers and
 the TPU path lands device batches via io/parquet.py et al.]
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson

from spark_rapids_tpu.columnar import dtypes as T


def _expand(path) -> List[str]:
    paths: List[str] = []
    for p in ([path] if isinstance(path, str) else list(path)):
        if os.path.isdir(p):
            # recursive walk: hive-partitioned layouts nest k=v dirs.
            # In-place dirs pruning skips metadata trees (_delta_log/,
            # _temporary/, .checkpoints/) and keeps traversal sorted.
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(("_", ".")))
                for f in sorted(files):
                    if not f.startswith(("_", ".")):
                        paths.append(os.path.join(root, f))
        else:
            matches = sorted(glob.glob(p))
            paths.extend(matches if matches else [p])
    if not paths:
        raise FileNotFoundError(f"no input files at {path}")
    return paths


def _discover_partitions(roots, paths: List[str]):
    """Hive-style partition columns from ``k=v`` directory segments.

    Returns (per-file value dicts, partition StructFields) — ((), ())
    when the layout is unpartitioned.  Values infer int64 when every
    non-null value parses as int (Spark's inference), else string."""
    from spark_rapids_tpu.columnar import dtypes as T
    root_list = [roots] if isinstance(roots, str) else list(roots)
    values: List[dict] = []
    keys: List[str] = []
    for p in paths:
        rel = None
        for r in root_list:
            if os.path.isdir(r) and os.path.abspath(p).startswith(
                    os.path.abspath(r) + os.sep):
                rel = os.path.relpath(p, r)
                break
        d = {}
        if rel:
            for seg in rel.split(os.sep)[:-1]:
                if "=" in seg:
                    from spark_rapids_tpu.io.parquet import HIVE_NULL
                    k, v = seg.split("=", 1)
                    d[k] = None if v == HIVE_NULL else v
                    if k not in keys:
                        keys.append(k)
        values.append(d)
    if not keys:
        return (), ()
    fields = []
    for k in keys:
        vs = [d.get(k) for d in values]
        try:
            ints = [None if v is None else int(v) for v in vs]
            dt = T.LongT
            for d, iv in zip(values, ints):
                d[k] = iv
        except (TypeError, ValueError):
            dt = T.StringT
        fields.append(T.StructField(k, dt, any(v is None for v in vs)))
    return values, tuple(fields)


class DataFrameReader:
    def __init__(self, session):
        self.session = session
        self._options: Dict[str, str] = {}
        self._schema: Optional[T.StructType] = None

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[str(key)] = value
        return self

    def options(self, **kw) -> "DataFrameReader":
        self._options.update(kw)
        return self

    def schema(self, s: T.StructType) -> "DataFrameReader":
        self._schema = s
        return self

    def _file_relation(self, path, fmt: str):
        from spark_rapids_tpu.io.parquet import orc_schema, parquet_schema
        from spark_rapids_tpu.plan.logical import ParquetRelation
        from spark_rapids_tpu.sql.dataframe import DataFrame

        paths = _expand(path)
        data_schema = self._schema or (
            orc_schema(paths) if fmt == "orc" else parquet_schema(paths))
        part_values, part_fields = _discover_partitions(path, paths)
        schema = T.StructType(tuple(data_schema.fields) + part_fields)
        return DataFrame(self.session, ParquetRelation(
            paths, schema, format=fmt,
            partition_values=list(part_values) or None,
            partition_fields=part_fields))

    def format(self, fmt: str) -> "DataFrameReader":
        self._format = str(fmt).lower()
        return self

    def load(self, path):
        fmt = getattr(self, "_format", "parquet")
        if fmt in ("delta", "iceberg", "parquet", "orc", "csv", "json",
                   "text", "avro"):
            return getattr(self, fmt)(path)
        raise ValueError(f"unknown read format {fmt!r}")

    def parquet(self, path):
        return self._file_relation(path, "parquet")

    def delta(self, path):
        """Delta Lake table read via transaction-log replay
        [REF: GpuDeltaLog / GpuDeltaParquetFileFormat]."""
        from spark_rapids_tpu.io.delta import delta_relation
        from spark_rapids_tpu.sql.dataframe import DataFrame
        return DataFrame(self.session, delta_relation(path))

    def orc(self, path):
        """[REF: GpuOrcScan.scala] — host pyarrow.orc decode + H2D."""
        return self._file_relation(path, "orc")

    def avro(self, path):
        """Flat-record avro via the built-in container codec
        [REF: GpuAvroScan.scala — host-parsed there too]."""
        import pyarrow as pa
        from spark_rapids_tpu.io.avro import avro_to_arrow
        paths = _expand(path)
        tbl = pa.concat_tables([avro_to_arrow(p) for p in paths],
                               promote_options="permissive")
        if self._schema is not None:
            # honor a user schema like the other formats: cast columns
            # onto the declared types, in declared order
            tbl = tbl.select(self._schema.field_names()).cast(pa.schema(
                [(f.name, T.to_arrow(f.dtype))
                 for f in self._schema.fields]))
        return self.session.createDataFrame(tbl)

    def iceberg(self, path):
        """Iceberg table read via metadata/manifest replay
        [REF: GpuIcebergParquetReader]."""
        from spark_rapids_tpu.io.iceberg import iceberg_relation
        from spark_rapids_tpu.sql.dataframe import DataFrame
        return DataFrame(self.session, iceberg_relation(path))

    def text(self, path):
        """Each line as one 'value' string column (spark.read.text)."""
        paths = _expand(path)
        rows = []
        for p in paths:
            with open(p, "r", errors="replace") as f:
                rows.extend(line.rstrip("\n") for line in f)
        return self.session.createDataFrame(
            pa.table({"value": pa.array(rows, type=pa.string())}))

    def csv(self, path, header: Optional[bool] = None):
        paths = _expand(path)
        if header is None:
            header = str(self._options.get("header", "false")).lower() in (
                "true", "1")
        read_opts = pacsv.ReadOptions(
            autogenerate_column_names=not header)
        convert = pacsv.ConvertOptions()
        if self._schema is not None:
            # user schema drives column types (and names when headerless)
            if not header:
                read_opts = pacsv.ReadOptions(
                    column_names=self._schema.field_names())
            convert = pacsv.ConvertOptions(column_types={
                f.name: T.to_arrow(f.dtype)
                for f in self._schema.fields})
        tables = [pacsv.read_csv(p, read_options=read_opts,
                                 convert_options=convert) for p in paths]
        tbl = pa.concat_tables(tables, promote_options="permissive")
        if not header and self._schema is None:
            tbl = tbl.rename_columns(
                [f"_c{i}" for i in range(tbl.num_columns)])
        return self.session.createDataFrame(tbl)

    def json(self, path):
        paths = _expand(path)
        parse = pajson.ParseOptions()
        if self._schema is not None:
            parse = pajson.ParseOptions(explicit_schema=pa.schema(
                [(f.name, T.to_arrow(f.dtype))
                 for f in self._schema.fields]))
        tables = [pajson.read_json(p, parse_options=parse) for p in paths]
        tbl = pa.concat_tables(tables, promote_options="permissive")
        return self.session.createDataFrame(tbl)


class DataFrameWriter:
    def __init__(self, df):
        self.df = df
        self._mode = "error"
        self._options: Dict[str, str] = {}
        self._partition_by: List[str] = []

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[str(key)] = value
        return self

    def partitionBy(self, *cols) -> "DataFrameWriter":
        """Hive-style dynamic-partition layout (k=v directories)
        [REF: GpuFileFormatDataWriter.scala]."""
        self._partition_by = [c for c in cols]
        return self

    def parquet(self, path: str):
        from spark_rapids_tpu.io.parquet import write_parquet
        write_parquet(self.df.toArrow(), path, self._mode,
                      partition_by=self._partition_by)

    def orc(self, path: str):
        from spark_rapids_tpu.io.parquet import write_parquet
        write_parquet(self.df.toArrow(), path, self._mode,
                      partition_by=self._partition_by, fmt="orc")

    def csv(self, path: str):
        import pyarrow.csv as pacsv
        table = self.df.toArrow()
        if os.path.exists(path) and self._mode == "overwrite":
            import shutil
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path) and self._mode in ("error",
                                                     "errorifexists"):
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        pacsv.write_csv(table, os.path.join(path, "part-00000.csv"))

    def json(self, path: str):
        table = self.df.toArrow()
        if os.path.exists(path) and self._mode == "overwrite":
            import shutil
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path) and self._mode in ("error",
                                                     "errorifexists"):
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        import json as _json
        rows = table.to_pylist()
        with open(os.path.join(path, "part-00000.json"), "w") as f:
            for r in rows:
                f.write(_json.dumps(r, default=str) + "\n")
