"""Iceberg read path: metadata.json → manifest list → manifests → scan.

[REF: iceberg/src/main/scala :: GpuIcebergParquetReader, iceberg scan
 metas; SURVEY §2.1 #31] — the reference plugs its GPU parquet reader
under Iceberg's scan planning.  Here the table format itself is
implemented against the public Iceberg spec (v1/v2): the current
snapshot's manifest list and manifest files (Avro — io/avro.py) flatten
into a data-file list with identity-transform partition values, which
feeds the engine's regular parquet scan stack (pruning/AQE/DPP apply).

Gated with clear errors: delete files (v2 row-level deletes),
non-identity partition transforms, non-parquet data files.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.io.avro import read_container


class IcebergProtocolError(NotImplementedError):
    pass


_PRIMITIVES = {
    "boolean": T.BooleanT, "int": T.IntegerT, "long": T.LongT,
    "float": T.FloatT, "double": T.DoubleT, "date": T.DateT,
    "string": T.StringT, "binary": T.BinaryT,
    "timestamp": T.TimestampT, "timestamptz": T.TimestampT,
}


def _parse_iceberg_type(t) -> T.DataType:
    if isinstance(t, str):
        if t in _PRIMITIVES:
            return _PRIMITIVES[t]
        if t.startswith("decimal("):
            p, s = t[len("decimal("):-1].split(",")
            return T.DecimalType(int(p), int(s))
        raise IcebergProtocolError(f"iceberg type {t!r} not supported")
    if isinstance(t, dict) and t.get("type") == "list":
        return T.ArrayType(_parse_iceberg_type(t["element"]))
    raise IcebergProtocolError(f"iceberg type {t!r} not supported")


def _current_schema_spec(md: dict) -> dict:
    schemas = md.get("schemas")
    if schemas:
        sid = md.get("current-schema-id", 0)
        return next((s for s in schemas if s.get("schema-id") == sid),
                    schemas[-1])
    return md["schema"]  # v1 single-schema layout


def _schema_from_metadata(md: dict) -> T.StructType:
    fields = []
    for f in _current_schema_spec(md)["fields"]:
        fields.append(T.StructField(
            f["name"], _parse_iceberg_type(f["type"]),
            not f.get("required", False)))
    return T.StructType(tuple(fields))


def _latest_metadata(table_path: str) -> str:
    meta_dir = os.path.join(table_path, "metadata")
    hint = os.path.join(meta_dir, "version-hint.text")
    if os.path.exists(hint):
        with open(hint) as f:
            v = f.read().strip()
        cand = os.path.join(meta_dir, f"v{v}.metadata.json")
        if os.path.exists(cand):
            return cand
    best: Optional[str] = None
    best_v = -1
    for fn in os.listdir(meta_dir) if os.path.isdir(meta_dir) else ():
        if fn.endswith(".metadata.json"):
            # 'v3.metadata.json' or catalog-written
            # '00003-<uuid>.metadata.json' — the version is the numeric
            # prefix (before any '-'), never the uuid digits
            stem = fn.split(".")[0].split("-")[0].lstrip("v")
            v = int(stem) if stem.isdigit() else 0
            if v > best_v:
                best_v, best = v, os.path.join(meta_dir, fn)
    if best is None:
        raise FileNotFoundError(
            f"not an iceberg table (no metadata/*.metadata.json): "
            f"{table_path}")
    return best


def _resolve_path(p: str, table_path: str) -> str:
    if p.startswith("file://"):
        p = p[len("file://"):]
    if os.path.isabs(p):
        return p
    return os.path.join(table_path, p)


def load_snapshot(table_path: str):
    """(table schema, partition field names, [(path, {pcol: value})],
    per-file deleted-position arrays | None)."""
    with open(_latest_metadata(table_path)) as f:
        md = json.load(f)
    schema = _schema_from_metadata(md)
    # identity partition columns from the default spec
    specs = md.get("partition-specs") or (
        [{"fields": md.get("partition-spec", [])}])
    spec_id = md.get("default-spec-id", 0)
    spec = next((s for s in specs if s.get("spec-id", 0) == spec_id),
                specs[-1] if specs else {"fields": []})
    part_cols: List[str] = []
    field_by_id = {f["id"]: f["name"]
                   for f in _current_schema_spec(md).get("fields", [])}
    for pf in spec.get("fields", []):
        if pf.get("transform", "identity") != "identity":
            raise IcebergProtocolError(
                f"partition transform {pf.get('transform')!r} is not "
                "supported (identity only)")
        part_cols.append(pf.get("name")
                         or field_by_id.get(pf.get("source-id")))

    snap_id = md.get("current-snapshot-id")
    if snap_id in (None, -1):
        return schema, part_cols, [], None
    snap = next(s for s in md.get("snapshots", [])
                if s.get("snapshot-id") == snap_id)
    files: List[tuple] = []
    if "manifest-list" in snap:
        ml_path = _resolve_path(snap["manifest-list"], table_path)
        _, entries = read_container(ml_path)
        manifests = [_resolve_path(e["manifest_path"], table_path)
                     for e in entries]
    else:  # v1 inline manifest array
        manifests = [_resolve_path(p, table_path)
                     for p in snap.get("manifests", [])]
    delete_files: List[str] = []
    for mpath in manifests:
        _, entries = read_container(mpath)
        for e in entries:
            status = e.get("status", 1)
            if status == 2:  # DELETED
                continue
            df = e["data_file"]
            content = df.get("content", 0)
            if content == 1:
                # v2 POSITION deletes: a parquet file of
                # (file_path, pos) rows — collected here, applied as
                # per-file row masks at scan [REF: iceberg spec
                # "Position Delete Files"; GpuDeleteFilter]
                delete_files.append(
                    _resolve_path(df["file_path"], table_path))
                continue
            if content != 0:
                raise IcebergProtocolError(
                    "iceberg EQUALITY delete files (content=2) are not "
                    "supported — compact the table, or read with the "
                    "reference engine")
            fmt = str(df.get("file_format", "PARQUET")).upper()
            if fmt != "PARQUET":
                raise IcebergProtocolError(
                    f"iceberg data format {fmt!r} not supported")
            part = df.get("partition") or {}
            files.append((_resolve_path(df["file_path"], table_path),
                          dict(part)))
    files = sorted(files, key=lambda t: t[0])
    deletes = None
    if delete_files:
        deletes = _load_position_deletes(
            delete_files, [p for p, _ in files], table_path)
    return schema, part_cols, files, deletes


def _load_position_deletes(delete_files: List[str],
                           data_paths: List[str], table_path: str):
    """Read position-delete parquet files → per-data-file sorted
    position arrays aligned with ``data_paths``.

    The spec's file_path values are the manifests' (possibly
    absolute/URI) paths; match both the raw string and the resolved
    local path so synthesized and real tables both hit."""
    import numpy as np
    import pyarrow.parquet as pq
    by_path = {}
    for i, p in enumerate(data_paths):
        by_path[p] = i
        by_path[os.path.abspath(p)] = i
    acc: dict = {}
    for dp in delete_files:
        tbl = pq.read_table(dp, columns=["file_path", "pos"])
        for fp, pos in zip(tbl.column("file_path").to_pylist(),
                           tbl.column("pos").to_pylist()):
            i = by_path.get(fp)
            if i is None:
                i = by_path.get(_resolve_path(fp, table_path))
            if i is None:
                continue  # deletes for a file not in this snapshot
            acc.setdefault(i, []).append(pos)
    out = [None] * len(data_paths)
    for i, lst in acc.items():
        out[i] = np.unique(np.asarray(lst, dtype=np.int64))
    return out


def iceberg_relation(table_path: str):
    from spark_rapids_tpu.plan.logical import ParquetRelation
    schema, part_cols, files, deletes = load_snapshot(table_path)
    data_fields = tuple(f for f in schema.fields
                        if f.name not in part_cols)
    part_fields = tuple(f for f in schema.fields if f.name in part_cols)
    paths = [p for p, _ in files]
    pvals = [pv for _, pv in files]
    out_schema = T.StructType(data_fields + part_fields)
    return ParquetRelation(
        paths, out_schema, format="parquet",
        partition_values=pvals if part_fields else None,
        partition_fields=part_fields, deletes=deletes)
