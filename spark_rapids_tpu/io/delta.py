"""Delta Lake read path: transaction-log snapshot reconstruction.

[REF: delta-lake/common/../GpuDeltaParquetFileFormat, GpuDeltaLog,
 RapidsDeltaUtils; SURVEY §2.1 #30] — the reference accelerates Delta
through its GPU parquet reader per Delta version module.  Here the log
protocol itself is implemented once (it is an open spec): JSON commits
+ optional parquet checkpoints replay into a snapshot {add-file set,
partition values, schema}, which then rides the engine's regular
parquet scan stack — so column pruning, row-group stats pruning, AQE
and DPP all apply to Delta tables for free.

Supported: commits, checkpoints (_last_checkpoint pointer), add/remove
reconciliation, partition values, schemaString. Gated with clear
errors: deletion vectors, column mapping (id/name modes).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.columnar import dtypes as T


class DeltaProtocolError(NotImplementedError):
    pass


_PRIMITIVES = {
    "string": T.StringT, "long": T.LongT, "integer": T.IntegerT,
    "short": T.ShortT, "byte": T.ByteT, "float": T.FloatT,
    "double": T.DoubleT, "boolean": T.BooleanT, "binary": T.BinaryT,
    "date": T.DateT, "timestamp": T.TimestampT,
}


def _parse_delta_type(t) -> T.DataType:
    if isinstance(t, str):
        if t in _PRIMITIVES:
            return _PRIMITIVES[t]
        if t.startswith("decimal("):
            p, s = t[len("decimal("):-1].split(",")
            return T.DecimalType(int(p), int(s))
        raise DeltaProtocolError(f"delta type {t!r} not supported")
    if isinstance(t, dict) and t.get("type") == "array":
        return T.ArrayType(_parse_delta_type(t["elementType"]))
    raise DeltaProtocolError(f"delta type {t!r} not supported")


def _parse_schema_string(s: str) -> T.StructType:
    spec = json.loads(s)
    fields = []
    for f in spec["fields"]:
        fields.append(T.StructField(f["name"], _parse_delta_type(
            f["type"]), bool(f.get("nullable", True))))
    return T.StructType(tuple(fields))


def _partition_value(raw: Optional[str], dt: T.DataType):
    """Delta stores partition values as strings (null = None)."""
    import datetime
    import decimal
    if raw is None:
        return None
    if isinstance(dt, (T.LongType, T.IntegerType, T.ShortType,
                       T.ByteType)):
        return int(raw)
    if isinstance(dt, (T.DoubleType, T.FloatType)):
        return float(raw)
    if isinstance(dt, T.BooleanType):
        return raw.lower() == "true"
    if isinstance(dt, T.DateType):
        return datetime.date.fromisoformat(raw)
    if isinstance(dt, T.TimestampType):
        v = datetime.datetime.fromisoformat(raw)
        if v.tzinfo is None:
            v = v.replace(tzinfo=datetime.timezone.utc)
        return v
    if isinstance(dt, T.DecimalType):
        return decimal.Decimal(raw)
    return raw


def _as_dict(v):
    """Arrow map columns deserialize as [(k, v), ...] — normalize."""
    if isinstance(v, list):
        return dict(v)
    return v or {}


class DeltaSnapshot:
    def __init__(self, schema: T.StructType, partition_columns: List[str],
                 files: List[Tuple[str, Dict, Optional[Dict]]]):
        self.schema = schema  # full table schema incl. partition cols
        self.partition_columns = partition_columns
        # [(abs path, raw partitionValues dict, DV descriptor | None)]
        self.files = files


def _apply_action(state: dict, action: dict) -> None:
    if "metaData" in action:
        md = action["metaData"]
        fmt = md.get("format", {}).get("provider", "parquet")
        if fmt != "parquet":
            raise DeltaProtocolError(f"delta data format {fmt!r}")
        cfg = _as_dict(md.get("configuration"))
        if cfg.get("delta.columnMapping.mode", "none") not in (
                "none", None):
            raise DeltaProtocolError(
                "delta column mapping (id/name mode) is not supported")
        state["schema"] = _parse_schema_string(md["schemaString"])
        state["partition_columns"] = list(md.get("partitionColumns", []))
    if "protocol" in action:
        p = action["protocol"]
        if int(p.get("minReaderVersion", 1)) > 2:
            feats = p.get("readerFeatures") or []
            unsupported = [f for f in feats
                           if f not in ("timestampNtz", "columnMapping",
                                        "deletionVectors")]
            if "columnMapping" in feats:
                raise DeltaProtocolError("delta column mapping feature")
            if unsupported:
                raise DeltaProtocolError(
                    f"delta reader features {unsupported} not supported")
    if "add" in action:
        a = action["add"]
        # deletion vectors decode at load (io/deletion_vectors.py) and
        # apply as a scan-time row mask
        state["files"][a["path"]] = (
            _as_dict(a.get("partitionValues")),
            _as_dict(a.get("deletionVector")) or None)
    if "remove" in action:
        state["files"].pop(action["remove"]["path"], None)


def _read_checkpoint(path: str, state: dict) -> None:
    import pyarrow.parquet as pq
    pf = pq.ParquetFile(path)
    # project away per-file stats/txn/commitInfo — only actions matter
    want = [c for c in ("metaData", "protocol", "add", "remove")
            if c in pf.schema_arrow.names]
    tbl = pf.read(columns=want)
    for row in tbl.to_pylist():
        action = {k: v for k, v in row.items() if v is not None}
        _apply_action(state, action)


def load_snapshot(table_path: str) -> DeltaSnapshot:
    log_dir = os.path.join(table_path, "_delta_log")
    if not os.path.isdir(log_dir):
        raise FileNotFoundError(
            f"not a delta table (no _delta_log): {table_path}")
    state = {"schema": None, "partition_columns": [], "files": {}}
    start_version = 0
    last_cp = os.path.join(log_dir, "_last_checkpoint")
    if os.path.exists(last_cp):
        with open(last_cp) as f:
            cp = json.load(f)
        v = int(cp["version"])
        parts = int(cp.get("parts", 0) or 0)
        if parts:
            cps = [os.path.join(
                log_dir, f"{v:020d}.checkpoint.{i + 1:010d}."
                         f"{parts:010d}.parquet") for i in range(parts)]
        else:
            cps = [os.path.join(log_dir, f"{v:020d}.checkpoint.parquet")]
        for p in cps:
            _read_checkpoint(p, state)
        start_version = v + 1
    versions = []
    for fn in os.listdir(log_dir):
        if fn.endswith(".json") and fn[:-5].isdigit():
            ver = int(fn[:-5])
            if ver >= start_version:
                versions.append((ver, fn))
    versions.sort()
    # Delta readers must verify commit contiguity — a gap means a
    # missing commit and a silently wrong snapshot
    for i, (ver, _) in enumerate(versions):
        if ver != start_version + i:
            raise DeltaProtocolError(
                f"delta log has a gap: expected version "
                f"{start_version + i}, found {ver}")
    for _, fn in versions:
        with open(os.path.join(log_dir, fn)) as f:
            for line in f:
                line = line.strip()
                if line:
                    _apply_action(state, json.loads(line))
    if state["schema"] is None:
        raise DeltaProtocolError(
            f"delta log at {table_path} has no metaData action")
    from urllib.parse import unquote
    # add.path is an RFC 2396 percent-encoded relative URI per the spec
    files = [(os.path.join(table_path, unquote(p)), pv, dv)
             for p, (pv, dv) in sorted(state["files"].items())]
    return DeltaSnapshot(state["schema"], state["partition_columns"],
                         files)


def delta_relation(table_path: str):
    """DeltaSnapshot → the engine's ParquetRelation (scan stack reuse)."""
    from spark_rapids_tpu.plan.logical import ParquetRelation
    snap = load_snapshot(table_path)
    part_cols = snap.partition_columns
    data_fields = tuple(f for f in snap.schema.fields
                        if f.name not in part_cols)
    part_fields = tuple(f for f in snap.schema.fields
                        if f.name in part_cols)
    by_name = {f.name: f for f in part_fields}
    paths = [p for p, _, _ in snap.files]
    pvals = [{k: _partition_value(v, by_name[k].dtype)
              for k, v in pv.items() if k in by_name}
             for _, pv, _ in snap.files]
    deletes = None
    if any(dv for _, _, dv in snap.files):
        from spark_rapids_tpu.io.deletion_vectors import read_dv
        deletes = [read_dv(dv, table_path) if dv else None
                   for _, _, dv in snap.files]
    schema = T.StructType(data_fields + part_fields)
    return ParquetRelation(
        paths, schema, format="parquet",
        partition_values=pvals if part_fields else None,
        partition_fields=part_fields, deletes=deletes)
