"""Typed config registry — the ``spark.rapids.*`` namespace.

Mirrors the reference's single-file typed ConfEntry builder DSL
[REF: sql-plugin/../RapidsConf.scala :: RapidsConf, ConfEntry, ConfBuilder]:
entries are declared once with type/doc/default, validated at startup, and
``docs/configs.md`` is generated from the registry so docs never drift.

The config namespace is kept byte-compatible with the reference
(``spark.rapids.sql.enabled`` etc.) so existing spark-rapids job configs
carry over; TPU-specific knobs live under ``spark.rapids.tpu.*``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional

_SIZE_RE = re.compile(r"^(\d+)([kKmMgGtT]?)[bB]?$")
_SIZE_MULT = {"": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_bytes(v) -> int:
    if isinstance(v, int):
        return v
    m = _SIZE_RE.match(str(v).strip())
    if not m:
        raise ValueError(f"cannot parse byte size {v!r}")
    return int(m.group(1)) * _SIZE_MULT[m.group(2).lower()]


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("true", "1", "yes"):
        return True
    if s in ("false", "0", "no"):
        return False
    raise ValueError(f"cannot parse boolean {v!r}")


@dataclasses.dataclass
class ConfEntry:
    key: str
    doc: str
    default: Any
    converter: Callable[[Any], Any]
    category: str = "sql"
    internal: bool = False
    startup_only: bool = False
    checker: Optional[Callable[[Any], bool]] = None
    check_msg: str = ""

    def convert(self, raw):
        v = self.converter(raw)
        if self.checker is not None and not self.checker(v):
            hint = f" ({self.check_msg})" if self.check_msg else ""
            raise ValueError(f"invalid value {v!r} for {self.key}{hint}")
        return v


class _Registry:
    def __init__(self):
        self.entries: Dict[str, ConfEntry] = {}

    def register(self, e: ConfEntry):
        if e.key in self.entries:
            raise ValueError(f"duplicate conf key {e.key}")
        self.entries[e.key] = e
        return e


REGISTRY = _Registry()


class ConfBuilder:
    """``conf(key).doc(...).boolean().create_with_default(x)`` builder DSL."""

    def __init__(self, key: str):
        self._key = key
        self._doc = ""
        self._category = "sql"
        self._internal = False
        self._startup = False
        self._converter: Callable = str
        self._checker = None
        self._check_msg = ""

    def doc(self, d: str) -> "ConfBuilder":
        self._doc = d
        return self

    def category(self, c: str) -> "ConfBuilder":
        self._category = c
        return self

    def internal(self) -> "ConfBuilder":
        self._internal = True
        return self

    def startup_only(self) -> "ConfBuilder":
        self._startup = True
        return self

    def boolean(self) -> "ConfBuilder":
        self._converter = _parse_bool
        return self

    def integer(self) -> "ConfBuilder":
        self._converter = int
        return self

    def double(self) -> "ConfBuilder":
        self._converter = float
        return self

    def string(self) -> "ConfBuilder":
        self._converter = str
        return self

    def bytes(self) -> "ConfBuilder":
        self._converter = parse_bytes
        return self

    def check(self, fn, msg="") -> "ConfBuilder":
        self._checker = fn
        self._check_msg = msg
        return self

    def create_with_default(self, default) -> ConfEntry:
        return REGISTRY.register(
            ConfEntry(
                key=self._key,
                doc=self._doc,
                default=default,
                converter=self._converter,
                category=self._category,
                internal=self._internal,
                startup_only=self._startup,
                checker=self._checker,
                check_msg=self._check_msg,
            )
        )


def conf(key: str) -> ConfBuilder:
    return ConfBuilder(key)


# ---------------------------------------------------------------------------
# Core entries (the reference's most load-bearing knobs, same keys)
# ---------------------------------------------------------------------------

SQL_ENABLED = (
    conf("spark.rapids.sql.enabled")
    .doc("Enable columnar acceleration on TPU. When false every operator "
         "runs on the CPU fallback path (the correctness oracle).")
    .boolean()
    .create_with_default(True)
)

EXPLAIN = (
    conf("spark.rapids.sql.explain")
    .doc("Explain mode for plan conversion: NONE, ALL, or NOT_ON_GPU "
         "(log every operator that could not be accelerated and why).")
    .string()
    .check(lambda v: v.upper() in ("NONE", "ALL", "NOT_ON_GPU",
                                   "NOT_ON_TPU"),
           "one of NONE, ALL, NOT_ON_GPU, NOT_ON_TPU")
    .create_with_default("NONE")
)

TEST_ENABLED = (
    conf("spark.rapids.sql.test.enabled")
    .doc("Test mode: raise instead of silently falling back to CPU for any "
         "operator not in the allow-list (see test.allowedNonGpu).")
    .category("test")
    .boolean()
    .create_with_default(False)
)

TEST_ALLOWED_NON_GPU = (
    conf("spark.rapids.sql.test.allowedNonGpu")
    .doc("Comma-separated operator class names permitted to fall back to "
         "CPU when test.enabled is on.")
    .category("test")
    .string()
    .create_with_default("")
)

BATCH_SIZE_BYTES = (
    conf("spark.rapids.sql.batchSizeBytes")
    .doc("Target device batch size; coalescing concatenates small batches "
         "up to this size. TPU default is smaller than the reference's 1g "
         "because padded static-shape buckets amplify footprint.")
    .bytes()
    .create_with_default(512 << 20)
)

BATCH_ROWS = (
    conf("spark.rapids.tpu.batchRows")
    .doc("Target device batch row count. Row counts are padded up to "
         "power-of-two buckets so XLA executables cache per (op, schema, "
         "bucket).")
    .integer()
    .create_with_default(1 << 20)
)

MIN_BUCKET_ROWS = (
    conf("spark.rapids.tpu.minBucketRows")
    .doc("Smallest static-shape row bucket.")
    .internal()
    .integer()
    .create_with_default(1 << 10)
)

AGG_BUCKET_ROWS = (
    conf("spark.rapids.tpu.agg.bucketRows")
    .doc("Grouped aggregates coalesce input batches up to this many live "
         "rows before each partial-pass kernel. 0 (default) disables "
         "coalescing: through a host tunnel each concat costs a count "
         "round trip plus a gather that EXCEEDS the saved per-chain "
         "dispatches (measured: TPC-H q1 2.7s uncoalesced vs 6.1s "
         "coalesced at 256k). On direct-attached hosts with many tiny "
         "partial batches, set 128k-512k.")
    .integer()
    .create_with_default(0)
)

AGG_SKIP_RATIO = (
    conf("spark.rapids.sql.agg.skipAggPassReductionRatio")
    .doc("When a grouped aggregate's first partial pass keeps more than "
         "this fraction of its input rows (grouping keys are nearly "
         "unique), later batches skip the per-batch sort+reduce and "
         "emit raw update buffers; the merge pass does the single real "
         "reduction [REF: GpuHashAggregateExec "
         "skipAggPassReductionRatio]. 1.0 disables skipping.")
    .double()
    .check(lambda v: 0.0 < v <= 1.0, "in (0, 1]")
    .create_with_default(0.9)
)

CONCURRENT_TASKS = (
    conf("spark.rapids.sql.concurrentGpuTasks")
    .doc("Number of tasks that may hold the device semaphore concurrently "
         "[REF: GpuSemaphore.scala].")
    .category("memory")
    .integer()
    .create_with_default(2)
)

MEMORY_FRACTION = (
    conf("spark.rapids.memory.gpu.allocFraction")
    .doc("Fraction of device HBM the budget arbiter may hand out before "
         "synchronous spill kicks in.")
    .category("memory")
    .double()
    .check(lambda v: 0.0 < v <= 1.0, "in (0, 1]")
    .create_with_default(0.85)
)

POOL_SIZE = (
    conf("spark.rapids.tpu.memory.poolSize")
    .doc("Explicit device memory budget in bytes; 0 means derive from "
         "allocFraction of detected HBM.")
    .category("memory")
    .bytes()
    .create_with_default(0)
)

HOST_SPILL_STORAGE = (
    conf("spark.rapids.memory.host.spillStorageSize")
    .doc("Host memory limit for spilled device buffers before they go to "
         "disk.")
    .category("memory")
    .bytes()
    .create_with_default(4 << 30)
)

SPILL_PATH = (
    conf("spark.rapids.tpu.spillPath")
    .doc("Directory for disk-tier spill files.")
    .category("memory")
    .string()
    .create_with_default("/tmp/tpuq-spill")
)

RETRY_MAX = (
    conf("spark.rapids.tpu.retry.maxAttempts")
    .doc("Max retry attempts per device/IO step before the engine gives "
         "up (OOM retries in the memory arbiter and every resilience "
         "failure domain share this one policy) "
         "[REF: RmmRapidsRetryIterator.scala :: withRetry].")
    .category("memory")
    .integer()
    .check(lambda v: v >= 1, "at least 1")
    .create_with_default(8)
)

RETRY_BACKOFF_BASE_MS = (
    conf("spark.rapids.tpu.retry.backoffBaseMs")
    .doc("Base delay for the retry policy's exponential backoff: attempt "
         "n sleeps ~base*2^(n-1) ms (capped by retry.backoffMaxMs, "
         "scaled by deterministic seeded jitter). 0 disables sleeping.")
    .category("memory")
    .double()
    .check(lambda v: v >= 0.0, "non-negative")
    .create_with_default(5.0)
)

RETRY_BACKOFF_MAX_MS = (
    conf("spark.rapids.tpu.retry.backoffMaxMs")
    .doc("Upper bound on a single retry backoff sleep in milliseconds.")
    .category("memory")
    .double()
    .check(lambda v: v >= 0.0, "non-negative")
    .create_with_default(1000.0)
)

RETRY_JITTER_SEED = (
    conf("spark.rapids.tpu.retry.jitterSeed")
    .doc("Seed for the retry policy's backoff jitter. Jitter is a pure "
         "function of (seed, domain, attempt), so a run is exactly "
         "reproducible under the same seed.")
    .category("memory")
    .integer()
    .create_with_default(0)
)

RETRY_BUDGET_PER_QUERY = (
    conf("spark.rapids.tpu.retry.budgetPerQuery")
    .doc("Total retries one query may spend across every failure domain "
         "before further faults are treated as exhausted (degrade or "
         "fail instead of retry-storming). 0 disables the budget.")
    .category("memory")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(64)
)

RETRY_HOST_DEGRADE = (
    conf("spark.rapids.tpu.retry.hostDegrade.enabled")
    .doc("On retry exhaustion in a degradable failure domain (execute, "
         "transfer, compile, spill_write, collective), trip the per-op "
         "circuit breaker and re-run the step on the host path instead "
         "of failing the query. Disable to surface a domain-tagged "
         "terminal error instead.")
    .category("memory")
    .boolean()
    .create_with_default(True)
)

SHUFFLE_MODE = (
    conf("spark.rapids.shuffle.mode")
    .doc("Shuffle transport: MULTITHREADED (host-path serialization, works "
         "everywhere), ICI (collective all_to_all across the slice — the "
         "UCX analog), or CACHE_ONLY.")
    .category("shuffle")
    .string()
    .check(lambda v: v.upper() in ("MULTITHREADED", "ICI", "CACHE_ONLY"),
           "one of MULTITHREADED, ICI, CACHE_ONLY")
    .create_with_default("MULTITHREADED")
)

EXCHANGE_MODE = (
    conf("spark.rapids.tpu.exchange.mode")
    .doc("ICI exchange transport: compiled (device-resident "
         "prepare/boundary SPMD programs — shuffle is one collective "
         "launch per stage seam), host (pin every exchange to the "
         "host-shuffle transport, the collective domain's degrade "
         "target), or auto (compiled when the mesh supports it). Only "
         "consulted when spark.rapids.shuffle.mode=ICI.")
    .category("shuffle")
    .string()
    .check(lambda v: v.lower() in ("compiled", "host", "auto"),
           "one of compiled, host, auto")
    .create_with_default("auto")
)

EXCHANGE_DONATE = (
    conf("spark.rapids.tpu.exchange.donate")
    .doc("Donate the sharded stage-input buffers to the compiled "
         "exchange's boundary program, so the wire consumes them "
         "instead of holding input and output co-resident in HBM. "
         "Disable to keep inputs alive through the collective (e.g. "
         "when debugging a mid-collective fault, at ~2x the exchange "
         "working set).")
    .category("shuffle")
    .boolean()
    .create_with_default(True)
)

SHUFFLE_THREADS = (
    conf("spark.rapids.shuffle.multiThreaded.writer.threads")
    .doc("Serializer thread pool size for MULTITHREADED shuffle.")
    .category("shuffle")
    .integer()
    .create_with_default(4)
)

MULTITHREADED_READ_THREADS = (
    conf("spark.rapids.sql.multiThreadedRead.numThreads")
    .doc("Thread pool size for the MULTITHREADED parquet reader "
         "(concurrent host decode + H2D per scan partition).")
    .category("io")
    .integer()
    .check(lambda v: v >= 1, ">= 1")
    .create_with_default(4)
)

SHUFFLE_PARTITIONS = (
    conf("spark.sql.shuffle.partitions")
    .doc("Default shuffle partition count (Spark core key, honored here).")
    .category("shuffle")
    .integer()
    .create_with_default(16)
)

METRICS_LEVEL = (
    conf("spark.rapids.sql.metrics.level")
    .doc("Metric verbosity: ESSENTIAL, MODERATE, DEBUG.")
    .string()
    .check(lambda v: v.upper() in ("ESSENTIAL", "MODERATE", "DEBUG"),
           "one of ESSENTIAL, MODERATE, DEBUG")
    .create_with_default("MODERATE")
)

INCOMPATIBLE_OPS = (
    conf("spark.rapids.sql.incompatibleOps.enabled")
    .doc("Enable operators whose results differ from Spark CPU in corner "
         "cases (documented per op).")
    .boolean()
    .create_with_default(False)
)

HAS_NANS = (
    conf("spark.rapids.sql.hasNans")
    .doc("Assume float data may contain NaNs (affects agg/join/sort "
         "eligibility for some ops).")
    .boolean()
    .create_with_default(True)
)

CAST_STRING_TO_FLOAT = (
    conf("spark.rapids.sql.castStringToFloat.enabled")
    .doc("Allow device string→float/double casts. Results can differ "
         "from Java's parseDouble by 1 ulp beyond 15 significant digits "
         "(same caveat as the reference's flag of this name).")
    .boolean()
    .create_with_default(False)
)

BROADCAST_THRESHOLD = (
    conf("spark.sql.autoBroadcastJoinThreshold")
    .doc("Max estimated size of a join side to broadcast it (gathered "
         "once, reused per stream partition — no exchange). -1 or 0 "
         "disables broadcast joins. Spark core key, honored here.")
    .bytes()
    .create_with_default(10 << 20)
)

PARQUET_DEVICE_DICT = (
    conf("spark.rapids.tpu.parquet.deviceDictDecode")
    .doc("Read parquet string columns dictionary-encoded and expand "
         "them ON DEVICE (indices + a small dictionary ride the "
         "host→device transfer instead of full byte matrices; the "
         "expansion is a device gather). The decode-on-device half of "
         "the reference's GPU parquet path that makes sense on TPU — "
         "decompression stays on host (no TPU decompress engine). "
         "[REF: GpuParquetScan.scala; SURVEY N6 phase-2]")
    .category("io")
    .boolean()
    .create_with_default(True)
)

JOIN_TARGET_ROWS = (
    conf("spark.rapids.tpu.join.targetRows")
    .doc("Row-capacity cap for one in-core sort-merge join. When either "
         "gathered side exceeds this many rows the join proactively "
         "hash-sub-partitions both sides ([REF: GpuSubPartitionHashJoin] "
         "— but size-driven, not OOM-reactive), recursing with fresh "
         "hash seeds on still-oversized sub-partitions, so sort/search "
         "kernels stay at or below the cap (exception: a single hot key "
         "cannot be spread by any key hash; after bounded recursion "
         "such a pair joins in-core, and the build side of a broadcast "
         "join is bounded by the broadcast byte threshold rather than "
         "this row cap — its streamed side honors the cap via bounded "
         "groups). XLA compile cost grows "
         "superlinearly with bucket size, so this bounds cold-compile "
         "time as well as memory. Join outputs are also re-batched to "
         "spark.rapids.tpu.batchRows chunks so downstream kernels never "
         "inherit an oversized bucket.")
    .integer()
    .create_with_default(1 << 18)
)

UDF_COMPILER_ENABLED = (
    conf("spark.rapids.sql.udfCompiler.enabled")
    .doc("Compile simple python UDFs (arithmetic, comparisons, "
         "conditionals, basic string methods) into device expressions "
         "via AST lowering — the compiled UDF fuses into the XLA "
         "program instead of crossing the arrow bridge. UDFs outside "
         "the subset silently fall back to the bridge.")
    .boolean()
    .create_with_default(False)
)

EXECUTOR_ID = (
    conf("spark.rapids.executor.id")
    .doc("This process's executor index in a multi-executor run "
         "(0-based). With executor.count > 1 the session joins the "
         "global device mesh via jax.distributed and scans serve only "
         "this executor's slice of source partitions.")
    .category("distributed")
    .startup_only()
    .integer()
    .create_with_default(0)
)

EXECUTOR_COUNT = (
    conf("spark.rapids.executor.count")
    .doc("Number of executor processes in the slice. >1 activates "
         "multi-executor mode: requires shuffle.mode=ICI, the "
         "jax.distributed coordinator address and the shuffle "
         "rendezvous address.")
    .category("distributed")
    .startup_only()
    .integer()
    .create_with_default(1)
)

COORDINATOR_ADDRESS = (
    conf("spark.rapids.executor.coordinator.address")
    .doc("host:port of the jax.distributed coordinator (process 0 "
         "binds it). Required when executor.count > 1.")
    .category("distributed")
    .startup_only()
    .string()
    .create_with_default("")
)

RENDEZVOUS_ADDRESS = (
    conf("spark.rapids.shuffle.rendezvous.address")
    .doc("host:port of the shuffle RendezvousCoordinator (driver-side "
         "barrier service). ICI exchanges use it for cross-process "
         "shape agreement and collective entry; required when "
         "executor.count > 1. [REF: RapidsShuffleInternalManagerBase "
         "— the MapOutputTracker-coordination analog]")
    .category("distributed")
    .startup_only()
    .string()
    .create_with_default("")
)

RENDEZVOUS_TIMEOUT = (
    conf("spark.rapids.shuffle.rendezvous.timeoutSec")
    .doc("Legacy alias for spark.rapids.tpu.rendezvous.timeoutMs "
         "(seconds). When set explicitly it wins over the timeoutMs "
         "key; prefer the millisecond key for new deployments.")
    .category("distributed")
    .double()
    .create_with_default(120.0)
)

RENDEZVOUS_TIMEOUT_MS = (
    conf("spark.rapids.tpu.rendezvous.timeoutMs")
    .doc("Deadline in milliseconds for every rendezvous barrier "
         "(allgather/enter). On expiry the coordinator fails ALL "
         "waiters of the stage (fail-together: nobody enters a "
         "collective that cannot complete — a hung ICI collective "
         "would wedge the whole slice); survivors then retry the "
         "stage at the next epoch under the shared retry policy.")
    .category("distributed")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(60000)
)

RENDEZVOUS_HEARTBEAT_MS = (
    conf("spark.rapids.tpu.rendezvous.heartbeatMs")
    .doc("Executor liveness heartbeat period. Each executor process "
         "registers with the rendezvous coordinator and renews its "
         "lease at this period; see rendezvous.leaseMs for the "
         "expiry. 0 disables the heartbeat (no liveness tracking).")
    .category("distributed")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(1500)
)

RENDEZVOUS_LEASE_MS = (
    conf("spark.rapids.tpu.rendezvous.leaseMs")
    .doc("Heartbeat lease: an executor that has not heartbeated for "
         "this long is declared dead, and the coordinator immediately "
         "poisons every in-flight and future rendezvous stage with a "
         "peer-tagged abort so survivors unwind in ~one lease instead "
         "of independent full stage deadlines. Must comfortably "
         "exceed heartbeatMs (default gives 10 beats per lease).")
    .category("distributed")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(15000)
)

RENDEZVOUS_SOCKET_TIMEOUT_MS = (
    conf("spark.rapids.tpu.rendezvous.socketTimeoutMs")
    .doc("Socket receive timeout for coordinator handler threads. A "
         "half-open client connection that never sends its request is "
         "dropped after this long instead of pinning a handler thread "
         "forever.")
    .category("distributed")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(10000)
)

ADAPTIVE_ENABLED = (
    conf("spark.sql.adaptive.enabled")
    .doc("Adaptive query execution: shuffle-read coalescing of small "
         "partitions and splitting of skewed ones, planned from measured "
         "partition sizes (Spark core key, honored here).")
    .category("aqe")
    .boolean()
    .create_with_default(True)
)

ADVISORY_PARTITION_SIZE = (
    conf("spark.sql.adaptive.advisoryPartitionSizeInBytes")
    .doc("Target bytes per shuffle-read partition after AQE coalescing/"
         "skew-splitting (Spark core key, honored here).")
    .category("aqe")
    .bytes()
    .create_with_default(64 << 20)
)

ADAPTIVE_PLANE_ENABLED = (
    conf("spark.rapids.tpu.adaptive.enabled")
    .doc("Master switch for the adaptive execution plane "
         "(spark_rapids_tpu/adaptive/): a cost model + replanner that "
         "spends the stats plane's recorded rows/bytes/partition sizes "
         "to rewrite the physical plan at stage boundaries — broadcast "
         "vs shuffled join strategy, skewed-partition splitting, and "
         "dynamic batch retargeting.  Each decision has its own "
         "sub-gate below; every decision taken is counted in "
         "tpuq_adaptive_decisions_total{kind} and rendered in EXPLAIN "
         "ANALYZE as adaptive=...")
    .category("aqe")
    .boolean()
    .create_with_default(False)
)

ADAPTIVE_JOIN_STRATEGY = (
    conf("spark.rapids.tpu.adaptive.joinStrategy.enabled")
    .doc("Adaptive join strategy selection: pick broadcast vs "
         "shuffled-hash per join from OBSERVED build-side cardinality — "
         "profile-store history for warm queries (adaptive.historyPath), "
         "upstream pump counts for cold ones — instead of the static "
         "planner estimate.  A build side that fits "
         "spark.sql.autoBroadcastJoinThreshold eliminates the exchange "
         "entirely.  Requires adaptive.enabled.")
    .category("aqe")
    .boolean()
    .create_with_default(True)
)

ADAPTIVE_SKEW_SPLIT = (
    conf("spark.rapids.tpu.adaptive.skewSplit.enabled")
    .doc("Adaptive skew splitting: when a shuffle exchange's recorded "
         "partition sizes show a skew factor above "
         "adaptive.skewThreshold, split the hot stream-side "
         "partition(s) into rank-interleaved sub-partitions and "
         "replicate the build side's matching partition, so one "
         "straggler stops serializing the stage.  Unlike hash "
         "sub-partitioning this spreads a SINGLE hot key.  Requires "
         "adaptive.enabled; inner/left/left_semi/left_anti joins only.")
    .category("aqe")
    .boolean()
    .create_with_default(True)
)

ADAPTIVE_SKEW_THRESHOLD = (
    conf("spark.rapids.tpu.adaptive.skewThreshold")
    .doc("Skew factor (hottest partition / mean) above which adaptive "
         "skew splitting triggers.  0 inherits "
         "spark.rapids.tpu.stats.skewThreshold so the replanner splits "
         "exactly the partitions the stats plane flags as SKEWED.")
    .category("aqe")
    .double()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(0.0)
)

ADAPTIVE_MAX_SPLITS = (
    conf("spark.rapids.tpu.adaptive.maxSplitsPerPartition")
    .doc("Upper bound on the number of rank-interleaved sub-partitions "
         "one hot partition may be split into — caps task fan-out (and "
         "build-side replication cost) no matter how hot the key is.")
    .category("aqe")
    .integer()
    .check(lambda v: v >= 2, "at least 2")
    .create_with_default(8)
)

ADAPTIVE_BATCH_RETARGET = (
    conf("spark.rapids.tpu.adaptive.batchRetarget.enabled")
    .doc("Dynamic batch retargeting: the AQE shuffle read plans its "
         "coalesce/split row target from the OBSERVED bytes/row of the "
         "exchange input (stats plane) instead of the static schema "
         "estimate, then snaps it to the shape plane's bucket ladder — "
         "variable-width columns stop under/over-filling read batches "
         "mid-query.  Requires adaptive.enabled.")
    .category("aqe")
    .boolean()
    .create_with_default(True)
)

ADAPTIVE_HISTORY_PATH = (
    conf("spark.rapids.tpu.adaptive.historyPath")
    .doc("JSONL profile store consulted for warm-query join decisions: "
         "the most recent recorded build-side bytes for a join's stable "
         "plan signature decides broadcast vs shuffled WITHOUT "
         "re-measuring.  Empty inherits spark.rapids.tpu.stats.storePath "
         "(decisions recorded there feed the next run automatically).")
    .category("aqe")
    .string()
    .create_with_default("")
)

DPP_ENABLED = (
    conf("spark.sql.optimizer.dynamicPartitionPruning.enabled")
    .doc("Dynamic partition pruning: joins on a hive-partition column "
         "evaluate the build side's distinct keys first and skip "
         "non-matching files of the probe-side scan (Spark core key, "
         "honored here).")
    .category("aqe")
    .boolean()
    .create_with_default(True)
)

ANSI_ENABLED = (
    conf("spark.sql.ansi.enabled")
    .doc("ANSI mode: arithmetic overflow and invalid casts raise instead "
         "of returning null (Spark core key, honored here).")
    .boolean()
    .create_with_default(False)
)

LORE_TAG = (
    conf("spark.rapids.sql.lore.tag")
    .doc("Exec class name (e.g. TpuSortMergeJoinExec) whose INPUT batches "
         "are dumped for offline replay [REF: GpuLore]. Empty disables.")
    .category("test")
    .string()
    .create_with_default("")
)

LORE_DUMP_PATH = (
    conf("spark.rapids.sql.lore.dumpPath")
    .doc("Directory for LORE dumps (parquet batches + meta).")
    .category("test")
    .string()
    .create_with_default("/tmp/tpuq-lore")
)

MEMORY_DEBUG = (
    conf("spark.rapids.memory.gpu.debug")
    .doc("NONE or STDOUT: track every spillable registration with its "
         "creation stack and report LEAK DETECTED for batches never "
         "closed [REF: cudf MemoryCleaner refcount debugging].")
    .category("memory")
    .string()
    .check(lambda v: v.upper() in ("NONE", "STDOUT"), "NONE or STDOUT")
    .create_with_default("NONE")
)

PROFILE_ENABLED = (
    conf("spark.rapids.profile.enabled")
    .doc("Capture a per-query device profile (jax/xplane trace, viewable "
         "in TensorBoard/XProf) [REF: spark-rapids-jni profiler].")
    .boolean()
    .create_with_default(False)
)

PROFILE_PATH = (
    conf("spark.rapids.profile.path")
    .doc("Directory for profile captures.")
    .string()
    .create_with_default("/tmp/tpuq-profile")
)

TRACE_ENABLED = (
    conf("spark.rapids.sql.trace.enabled")
    .doc("Per-query span tracing (the NVTX-range analog): every exec's "
         "partition pump and internal stages (compile, transfer, compute, "
         "collective) record spans, exported as Chrome-trace JSON "
         "(chrome://tracing / Perfetto) plus a per-operator self-time vs "
         "total-time rollup.")
    .boolean()
    .create_with_default(False)
)

TRACE_PATH = (
    conf("spark.rapids.sql.trace.path")
    .doc("Directory for Chrome-trace exports "
         "(query-<id>.trace.json per traced query).")
    .string()
    .create_with_default("/tmp/tpuq-trace")
)

QUERY_LOG_PATH = (
    conf("spark.rapids.sql.queryLog.path")
    .doc("JSONL file appended with one entry per executed query: plan "
         "tree, device/fallback report, all metrics at their levels, span "
         "rollup, and cross-links to trace/profile/LORE artifacts. Empty "
         "disables the file (session.query_history() still records).")
    .string()
    .create_with_default("")
)

QUERY_LOG_MAX_EVENTS = (
    conf("spark.rapids.sql.queryLog.maxEvents")
    .doc("Span cap per traced query; spans beyond the cap are counted as "
         "dropped rather than recorded (bounds tracer memory on "
         "pathological plans).")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(100000)
)

STATS_ENABLED = (
    conf("spark.rapids.tpu.stats.enabled")
    .doc("Per-operator runtime statistics (the stats plane): every exec "
         "pump boundary records observed rows/batches/bytes and batch-"
         "shape histograms, exchanges record per-partition sizes with a "
         "skew factor, and df.explain('analyze') / "
         "session.last_query_profile() surface the result. Off by "
         "default — each pumped device batch pays one device sync for "
         "its live-row count; df.explain('analyze') enables it for its "
         "own execution regardless.")
    .category("observability")
    .boolean()
    .create_with_default(False)
)

STATS_LEVEL = (
    conf("spark.rapids.tpu.stats.level")
    .doc("BASIC records rows/batches/bytes and batch-shape histograms; "
         "FULL adds per-column observed null ratios (one extra device "
         "sync per nullable column per batch).")
    .category("observability")
    .string()
    .check(lambda v: v.upper() in ("BASIC", "FULL"), "BASIC or FULL")
    .create_with_default("BASIC")
)

STATS_STORE_PATH = (
    conf("spark.rapids.tpu.stats.storePath")
    .doc("JSONL profile store appended with one record per executed "
         "query: per-operator observed stats keyed by a stable plan-"
         "node signature, plus exchange skew summaries. Read by "
         "python -m spark_rapids_tpu.utils.profile (top/skew/diff) and "
         "consultable by future planners across runs. Empty disables.")
    .category("observability")
    .string()
    .create_with_default("")
)

STATS_SKEW_THRESHOLD = (
    conf("spark.rapids.tpu.stats.skewThreshold")
    .doc("An exchange partition-size skew factor (max/mean) above this "
         "is reported as skewed in profiles, explain('analyze') and "
         "the profiler CLI skew report.")
    .category("observability")
    .double()
    .check(lambda v: v > 1.0, "> 1.0")
    .create_with_default(2.0)
)

ATTRIBUTION_ENABLED = (
    conf("spark.rapids.tpu.attribution.enabled")
    .doc("Per-query wall-clock attribution (the time books): fold trace "
         "spans, telemetry counter deltas and op/exchange stats into "
         "exclusive buckets (queue wait, semaphore wait, compile, kernel "
         "dispatch, exchange collectives, host shuffle, spill/restore "
         "I/O, cache, pump idle, host fallback) that sum to the query's "
         "end-to-end wall time within closeTolerance, with any gap "
         "reported explicitly as unaccounted. Also arms the flight "
         "recorder: a bounded ring of recent spans/health/retry/cancel "
         "events dumped atomically as query-<id>.blackbox.json when a "
         "query dies (timeout, cancel, error) or health degrades. On by "
         "default — reuses the existing span/counter instrumentation, "
         "no new timers on the pump hot path.")
    .category("observability")
    .boolean()
    .create_with_default(True)
)

ATTRIBUTION_RING_SIZE = (
    conf("spark.rapids.tpu.attribution.ringSize")
    .doc("Flight-recorder ring capacity: the last N closed spans and the "
         "last N health/retry/cancel events are retained per query "
         "(oldest evicted first) and shipped in the black box.")
    .category("observability")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(256)
)

ATTRIBUTION_CLOSE_TOLERANCE = (
    conf("spark.rapids.tpu.attribution.closeTolerance")
    .doc("Fraction of end-to-end wall time the unaccounted remainder may "
         "reach before the attribution is reported as NOT CLOSED (the "
         "gap is always reported either way, never absorbed into "
         "another bucket).")
    .category("observability")
    .double()
    .check(lambda v: 0.0 < v <= 1.0, "in (0, 1]")
    .create_with_default(0.10)
)

ATTRIBUTION_BLACKBOX_PATH = (
    conf("spark.rapids.tpu.attribution.blackboxPath")
    .doc("Directory for flight-recorder dumps "
         "(query-<id>.blackbox.json, written atomically via "
         "tmp+rename). Empty disables dumping.")
    .category("observability")
    .string()
    .create_with_default("/tmp/tpuq-blackbox")
)

ATTRIBUTION_BLACKBOX_MAX = (
    conf("spark.rapids.tpu.attribution.blackboxMaxDumps")
    .doc("Cap on black-box files kept in blackboxPath; when a new dump "
         "would exceed it the oldest files are evicted first.")
    .category("observability")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(64)
)

QUERY_TIMEOUT_MS = (
    conf("spark.rapids.tpu.query.timeoutMs")
    .doc("Per-query deadline in milliseconds, enforced in-process by "
         "the cooperative cancellation layer (runtime/cancel.py): when "
         "a query exceeds it, every blocking boundary raises "
         "QueryCancelled(reason='deadline') and the engine reclaims "
         "all of the query's resources (semaphore permits, HBM "
         "reservations, spill files). An explicit "
         "collect(timeout_ms=...) overrides this. <= 0 disables.")
    .category("lifecycle")
    .integer()
    .create_with_default(0)
)

CANCEL_POLL_MS = (
    conf("spark.rapids.tpu.query.cancelPollMs")
    .doc("Upper bound on how long any blocking wait (semaphore, retry "
         "backoff, spill IO, shuffle, rendezvous) may park before "
         "re-polling the query's CancelToken. Cancels and deadline "
         "expiries surface within ~2x this interval; registered "
         "waiters (the device semaphore) wake immediately.")
    .category("lifecycle")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(50)
)

FAULT_INJECT = (
    conf("spark.rapids.tpu.test.injectOomAtAlloc")
    .doc("Force an OOM at the Nth device allocation (test hook, mirrors "
         "RmmSpark.forceRetryOOM). -1 disables.")
    .category("test")
    .internal()
    .integer()
    .create_with_default(-1)
)

INJECT_EXECUTE_AT = (
    conf("spark.rapids.tpu.test.injectExecuteErrorAt")
    .doc("Raise an injected device error at the Nth kernel execution "
         "(resilience test hook, the faultinj analog). -1 disables.")
    .category("test")
    .internal()
    .integer()
    .create_with_default(-1)
)

INJECT_TRANSFER_AT = (
    conf("spark.rapids.tpu.test.injectTransferErrorAt")
    .doc("Raise an injected device error at the Nth device→host "
         "transfer. -1 disables.")
    .category("test")
    .internal()
    .integer()
    .create_with_default(-1)
)

INJECT_TRANSIENT_COUNT = (
    conf("spark.rapids.tpu.test.injectTransientCount")
    .doc("How many injected device errors are transient (recoverable by "
         "the retry policy) before they turn terminal. Legacy alias for "
         "the execute/transfer domains' inject.<domain>.transientCount.")
    .category("test")
    .internal()
    .integer()
    .create_with_default(0)
)

# Engine failure domains — every device/IO boundary the resilience layer
# guards.  Each domain gets an independently armable injection pair:
# ``spark.rapids.tpu.test.inject.<domain>.at`` (fire from the Nth call
# on; -1 disables) and ``.transientCount`` (transient fires before the
# fault turns terminal / the domain disarms).
FAILURE_DOMAINS = ("execute", "transfer", "alloc", "spill_write",
                   "spill_read", "shuffle_ser", "shuffle_exchange",
                   "collective", "compile", "rendezvous", "peer_loss",
                   "tenancy")

INJECT_DOMAIN_AT: Dict[str, ConfEntry] = {}
INJECT_DOMAIN_TRANSIENT: Dict[str, ConfEntry] = {}
for _dom in FAILURE_DOMAINS:
    INJECT_DOMAIN_AT[_dom] = (
        conf(f"spark.rapids.tpu.test.inject.{_dom}.at")
        .doc(f"Arm the '{_dom}' failure domain: raise an injected fault "
             "from its Nth call on (resilience test hook, the faultinj "
             "analog). -1 disables.")
        .category("test")
        .internal()
        .integer()
        .create_with_default(-1)
    )
    INJECT_DOMAIN_TRANSIENT[_dom] = (
        conf(f"spark.rapids.tpu.test.inject.{_dom}.transientCount")
        .doc(f"How many '{_dom}' injected faults are transient before "
             "they turn terminal (0 = the first fire is terminal).")
        .category("test")
        .internal()
        .integer()
        .create_with_default(0)
    )
del _dom

TELEMETRY_ENABLED = (
    conf("spark.rapids.tpu.telemetry.enabled")
    .doc("Continuous process telemetry: a background sampler snapshots "
         "the metrics registry (HBM arbiter, spill tiers, device "
         "semaphore, kernel cache, shuffle, pump pool) into a JSONL "
         "time series and a Prometheus text-format dump. The registry "
         "itself always updates; this only gates the sampler/sinks.")
    .category("telemetry")
    .boolean()
    .create_with_default(False)
)

LOCKDEP_ENABLED = (
    conf("spark.rapids.tpu.lockdep.enabled")
    .doc("Lockdep-style runtime watchdog: wraps the engine's "
         "threading.Lock/RLock/Condition instances, records the "
         "process-wide lock acquisition-order graph, and reports any "
         "edge that closes a cycle (a latent deadlock) from a single "
         "observation of both orders. Diagnostic; adds per-acquisition "
         "bookkeeping overhead.")
    .category("telemetry")
    .boolean()
    .create_with_default(False)
)

LOCKDEP_RAISE_ON_CYCLE = (
    conf("spark.rapids.tpu.lockdep.raiseOnCycle")
    .doc("With lockdep enabled, raise LockOrderViolation at the "
         "acquisition that closes a cycle instead of only recording it "
         "for the violations report.")
    .category("telemetry")
    .boolean()
    .create_with_default(False)
)

TELEMETRY_PERIOD_MS = (
    conf("spark.rapids.tpu.telemetry.samplePeriodMs")
    .doc("Sampler period in milliseconds.")
    .category("telemetry")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(1000)
)

TELEMETRY_SINK_PATH = (
    conf("spark.rapids.tpu.telemetry.sinkPath")
    .doc("JSONL time-series sink: one line per sample with every "
         "counter/gauge value and histogram summary. Empty disables "
         "the JSONL sink.")
    .category("telemetry")
    .string()
    .create_with_default("/tmp/tpuq-telemetry/metrics.jsonl")
)

TELEMETRY_PROM_PATH = (
    conf("spark.rapids.tpu.telemetry.promPath")
    .doc("Prometheus text exposition dump, atomically rewritten every "
         "sample — scrape it with node_exporter's textfile collector "
         "or serve the file. Empty disables the dump.")
    .category("telemetry")
    .string()
    .create_with_default("/tmp/tpuq-telemetry/metrics.prom")
)

HEALTH_SPILL_RATIO = (
    conf("spark.rapids.tpu.telemetry.health.spillRatio")
    .doc("WARN when one query's spilled bytes exceed this fraction of "
         "the bytes it reserved (the working set does not fit the HBM "
         "budget).")
    .category("telemetry")
    .double()
    .check(lambda v: v >= 0.0, "non-negative")
    .create_with_default(0.5)
)

HEALTH_SEM_WAIT_RATIO = (
    conf("spark.rapids.tpu.telemetry.health.semaphoreWaitRatio")
    .doc("WARN when one query's cumulative device-admission wait "
         "exceeds this fraction of its wall time (semaphore "
         "saturation: concurrentGpuTasks is the bottleneck).")
    .category("telemetry")
    .double()
    .check(lambda v: v >= 0.0, "non-negative")
    .create_with_default(0.5)
)

HEALTH_COMPILE_STORM = (
    conf("spark.rapids.tpu.telemetry.health.compileStorm")
    .doc("WARN when one query triggers more than this many XLA "
         "compiles (shape buckets / kernel fingerprints are not being "
         "reused).")
    .category("telemetry")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(64)
)

# -- shape plane + persistent kernel cache (runtime/shapes.py +
#    runtime/kernel_cache.py) ------------------------------------------------


def _valid_ladder(v) -> bool:
    """CSV of strictly-increasing positive row counts ('' = unset)."""
    s = str(v).strip()
    if not s:
        return True
    try:
        rungs = [int(x.strip()) for x in s.split(",")]
    except ValueError:
        return False
    return (all(r > 0 for r in rungs)
            and all(a < b for a, b in zip(rungs, rungs[1:])))


KERNEL_CACHE_DIR = (
    conf("spark.rapids.tpu.kernel.cacheDir")
    .doc("Directory for the persistent (on-disk) XLA compilation cache. "
         "Compiled executables survive process restarts, so a warm "
         "QueryServer restart pays zero hot-path compiles. The directory "
         "carries a manifest versioned on (jax, jaxlib, engine); a "
         "version mismatch invalidates the cache wholesale. Empty "
         "(default) falls back to the SPARK_RAPIDS_TPU_XLA_CACHE "
         "environment variable. Ignored on the XLA:CPU backend, whose "
         "AOT cache entries are unsafe to reload.")
    .category("kernel")
    .string()
    .create_with_default("")
)

KERNEL_BUCKETING = (
    conf("spark.rapids.tpu.kernel.bucketing")
    .doc("Batch-shape bucketing policy of the shape plane: 'pow2' pads "
         "device batch capacities up to power-of-two row buckets, "
         "'ladder' pads up to the explicit rung list in "
         "kernel.bucketLadder (pow2 above the top rung), 'off' disables "
         "re-bucketing at the exec pump boundary. Fewer distinct shapes "
         "means fewer (op, schema, bucket) XLA compiles.")
    .category("kernel")
    .string()
    .check(lambda v: str(v).lower() in ("off", "pow2", "ladder"),
           "one of off, pow2, ladder")
    .create_with_default("pow2")
)

KERNEL_BUCKET_LADDER = (
    conf("spark.rapids.tpu.kernel.bucketLadder")
    .doc("Comma-separated strictly-increasing row-count rungs for "
         "kernel.bucketing=ladder, e.g. '1024,8192,65536,1048576'. "
         "Capacities above the top rung fall back to pow2 rounding. "
         "Empty means ladder mode behaves like pow2.")
    .category("kernel")
    .string()
    .check(_valid_ladder, "comma-separated strictly-increasing "
                          "positive integers")
    .create_with_default("")
)

KERNEL_MAX_PAD_FRACTION = (
    conf("spark.rapids.tpu.kernel.maxPadFraction")
    .doc("Upper bound on the padding a bucket may introduce, as "
         "(bucket - capacity) / bucket. A rung that would exceed it is "
         "rejected in favor of the batch's pow2 bucket, trading a "
         "possible extra compile for bounded pad-waste bytes.")
    .category("kernel")
    .double()
    .check(lambda v: 0.0 <= v < 1.0, "in [0, 1)")
    .create_with_default(0.75)
)

KERNEL_BACKEND = (
    conf("spark.rapids.tpu.kernel.backend")
    .doc("Kernel-plane backend for the fused hash-join / segmented-sort "
         "/ hash-agg kernels: 'jnp' is the pure jax.numpy reference, "
         "'fused' the single-program XLA hash/tiled-rank kernels, "
         "'pallas' adds the Mosaic VPU hash kernel (TPU only), 'auto' "
         "picks pallas on TPU and fused elsewhere (except sort, whose "
         "tiled form only pays on TPU). Non-jnp backends degrade down "
         "the pallas>fused>jnp ladder on detected 64-bit hash "
         "collisions or unhashable keys, so results are always exact. "
         "See docs/kernels.md.")
    .category("kernel")
    .string()
    .check(lambda v: str(v).lower() in ("auto", "pallas", "fused", "jnp"),
           "one of auto, pallas, fused, jnp")
    .create_with_default("auto")
)

EXEC_PUMP_DEPTH = (
    conf("spark.rapids.tpu.exec.pumpDepth")
    .doc("Batches kept in flight by the double-buffered exec pump: each "
         "operator's output iterator is pre-pulled up to this depth so "
         "JAX async dispatch overlaps the producer's H2D/compute with "
         "the consumer's compute/D2H. 1 disables prefetch. Bounded "
         "small on purpose — holding all outputs alive costs ~60% "
         "exchange bandwidth (utils/exchange_bench.py).")
    .category("kernel")
    .integer()
    .check(lambda v: 1 <= int(v) <= 8, "in [1, 8]")
    .create_with_default(2)
)

KERNEL_WARMUP_ON_START = (
    conf("spark.rapids.tpu.kernel.warmupOnStart")
    .doc("QueryServer construction pre-executes the warmup plans handed "
         "to it (session.warmup), compiling the op x bucket matrix "
         "outside any query window — so the first tenant query never "
         "pays XLA compile and never trips the compile-storm health "
         "WARN. Disable to defer compilation to first use.")
    .category("kernel")
    .boolean()
    .create_with_default(True)
)


# -- whole-stage fusion plane (spark_rapids_tpu/fusion/) --------------------

FUSION_ENABLED = (
    conf("spark.rapids.tpu.fusion.enabled")
    .doc("Master switch for the whole-stage fusion plane "
         "(spark_rapids_tpu/fusion/): after plan conversion, maximal "
         "chains of fusable per-batch map operators (project / filter / "
         "cast chains) are stitched into FusedStageExec regions, each "
         "lowered to ONE jitted XLA program — intermediate batches stay "
         "device-resident SSA values inside the program, and the pump / "
         "pad-mask / shape-bucket boundary is paid once per region "
         "instead of once per operator.  Region boundaries are "
         "exchanges, stateful or non-jitable operators (limits, UDF "
         "fallbacks, collect aggregates) and anything whose fusion hook "
         "the fusion-purity analysis cannot prove host-pull-free.  A "
         "region that fails to compile falls open to the unfused pump "
         "chain (counted in tpuq_fusion_fallback_total); answers are "
         "bit-identical either way (tests/test_fusion.py).")
    .category("fusion")
    .boolean()
    .create_with_default(False)
)

FUSION_MAX_OPS = (
    conf("spark.rapids.tpu.fusion.maxOpsPerRegion")
    .doc("Upper bound on the member operators stitched into one fused "
         "region.  A chain longer than this splits into consecutive "
         "regions, bounding single-program XLA compile time; raising it "
         "trades compile latency for fewer dispatch boundaries.")
    .category("fusion")
    .integer()
    .check(lambda v: 2 <= int(v) <= 64, "in [2, 64]")
    .create_with_default(16)
)

FUSION_MODE = (
    conf("spark.rapids.tpu.fusion.mode")
    .doc("Region-selection policy: 'auto' fuses only chains of 2+ "
         "fusable operators (a singleton region saves nothing over the "
         "op's own cached kernel), 'aggressive' also wraps singleton "
         "fusable ops so every map rides region bookkeeping (useful to "
         "exercise the plane), 'off' disables region selection even "
         "when fusion.enabled is true.")
    .category("fusion")
    .string()
    .check(lambda v: str(v).lower() in ("auto", "off", "aggressive"),
           "one of auto, off, aggressive")
    .create_with_default("auto")
)


# -- multi-tenant query service (runtime/scheduler.py + sql/server.py) ------
#
# Per-tenant overrides ride a dynamic key family the scheduler reads at
# tenant creation:
#   spark.rapids.tpu.scheduler.tenant.<name>.weight        (double)
#   spark.rapids.tpu.scheduler.tenant.<name>.maxInFlight   (int)
#   spark.rapids.tpu.scheduler.tenant.<name>.maxQueued     (int)
#   spark.rapids.tpu.scheduler.tenant.<name>.hbmShare      (double)
#   spark.rapids.tpu.scheduler.tenant.<name>.sloP99Ms      (int)
# Unlisted tenants get the tenantWeight/tenantMaxInFlight/tenantMaxQueued/
# tenantHbmShare/tenantSloP99Ms defaults below.

SCHED_MAX_CONCURRENT = (
    conf("spark.rapids.tpu.scheduler.maxConcurrentQueries")
    .doc("How many admitted queries may execute concurrently across "
         "ALL tenants. Queries beyond this wait in their tenant's "
         "queue until the fairness scheduler (weighted deficit "
         "round-robin across tenants, priority lanes within a tenant) "
         "grants them a run slot. This caps whole queries; "
         "spark.rapids.sql.concurrentGpuTasks still caps the "
         "per-partition device admission inside each running query.")
    .category("scheduler")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(4)
)

SCHED_MAX_QUEUED = (
    conf("spark.rapids.tpu.scheduler.maxQueuedQueries")
    .doc("Global cap on queries waiting for a run slot, across all "
         "tenants. A submission beyond it is rejected with "
         "QueryRejected(reason='queue_full').")
    .category("scheduler")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(256)
)

SCHED_TENANT_WEIGHT = (
    conf("spark.rapids.tpu.scheduler.tenantWeight")
    .doc("Default fair-share weight of a tenant in the deficit "
         "round-robin dispatcher: a tenant with weight 2 is granted "
         "run slots twice as often as a weight-1 tenant under "
         "contention. Per-tenant override: "
         "spark.rapids.tpu.scheduler.tenant.<name>.weight.")
    .category("scheduler")
    .double()
    .check(lambda v: v >= 0.01, ">= 0.01")
    .create_with_default(1.0)
)

SCHED_TENANT_MAX_IN_FLIGHT = (
    conf("spark.rapids.tpu.scheduler.tenantMaxInFlight")
    .doc("Default per-tenant cap on concurrently RUNNING queries. "
         "Submissions beyond it queue (they are not rejected). "
         "Per-tenant override: "
         "spark.rapids.tpu.scheduler.tenant.<name>.maxInFlight.")
    .category("scheduler")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(4)
)

SCHED_TENANT_MAX_QUEUED = (
    conf("spark.rapids.tpu.scheduler.tenantMaxQueued")
    .doc("Default per-tenant cap on QUEUED queries. A submission "
         "beyond it is rejected with "
         "QueryRejected(reason='tenant_queue_full'). Per-tenant "
         "override: spark.rapids.tpu.scheduler.tenant.<name>.maxQueued.")
    .category("scheduler")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(64)
)

SCHED_TENANT_HBM_SHARE = (
    conf("spark.rapids.tpu.scheduler.tenantHbmShare")
    .doc("Default per-tenant HBM-reservation share, enforced as the "
         "fraction of maxConcurrentQueries run slots the tenant may "
         "hold at once (each running query may reserve up to the full "
         "HBM pool, so bounding a tenant's share of run slots bounds "
         "its share of device memory pressure). Per-tenant override: "
         "spark.rapids.tpu.scheduler.tenant.<name>.hbmShare.")
    .category("scheduler")
    .double()
    .check(lambda v: 0.0 < v <= 1.0, "in (0, 1]")
    .create_with_default(1.0)
)

SCHED_SHED_QUEUE_DEPTH = (
    conf("spark.rapids.tpu.scheduler.shed.queueDepth")
    .doc("Load-shed watermark on total service depth (queued + running "
         "queries): a submission arriving at or above it is shed with "
         "QueryRejected(reason='shed_queue_depth'), counted in "
         "tpuq_admission_shed_total and WARNed by the health "
         "evaluator, instead of joining a queue that can no longer "
         "drain within any useful deadline.")
    .category("scheduler")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(128)
)

SCHED_SHED_SPILL_RATIO = (
    conf("spark.rapids.tpu.scheduler.shed.spillRatio")
    .doc("Load-shed watermark on spill pressure: when the host spill "
         "tier's occupancy fraction (DeviceMemoryManager.spill_pressure) "
         "is at or above this, new submissions are shed with "
         "QueryRejected(reason='shed_spill_pressure') BEFORE the "
         "arbiter starts thrashing the disk tier.")
    .category("scheduler")
    .double()
    .check(lambda v: v > 0.0, "positive")
    .create_with_default(0.85)
)

SCHED_SHED_SEM_SATURATION = (
    conf("spark.rapids.tpu.scheduler.shed.semaphoreSaturation")
    .doc("Load-shed watermark on device-admission saturation: "
         "(semaphore holders + blocked waiters) / permits at or above "
         "this sheds new submissions with "
         "QueryRejected(reason='shed_semaphore_saturation'). The "
         "default 4.0 means: shed when 4x more tasks want the device "
         "than it admits.")
    .category("scheduler")
    .double()
    .check(lambda v: v > 0.0, "positive")
    .create_with_default(4.0)
)

SCHED_PREEMPT_ENABLED = (
    conf("spark.rapids.tpu.scheduler.preempt.enabled")
    .doc("Let the scheduler cooperatively preempt running queries: "
         "when a waiter has starved past preempt.graceMs the arbiter "
         "suspends a victim (largest-runtime query of the most "
         "over-share tenant) at its next pump boundary — permits "
         "released, resident batches spilled through the HBM tiers — "
         "admits the waiter, and resumes the victim bit-identically "
         "once capacity frees. Off by default: preemption trades "
         "victim latency for waiter fairness and should be an "
         "operator's explicit choice.")
    .category("scheduler")
    .boolean()
    .create_with_default(False)
)

SCHED_PREEMPT_GRACE_MS = (
    conf("spark.rapids.tpu.scheduler.preempt.graceMs")
    .doc("How long a queued query must wait before the preemption "
         "arbiter considers suspending a running victim on its "
         "behalf. Small values make the scheduler aggressive "
         "(hot-potato slots); large values approach "
         "fairness-by-politeness.")
    .category("scheduler")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(500)
)

SCHED_PREEMPT_MIN_RUN_MS = (
    conf("spark.rapids.tpu.scheduler.preempt.minRunMs")
    .doc("A running query younger than this (measured from its grant, "
         "and re-armed at each resume) is never picked as a "
         "preemption victim — the anti-thrash floor that guarantees "
         "forward progress under sustained overload.")
    .category("scheduler")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(250)
)

SCHED_QUEUE_SHAPING = (
    conf("spark.rapids.tpu.scheduler.queueShaping")
    .doc("Derive each tenant's EFFECTIVE queued-query cap from its "
         "fair-share weight (ceil(weight/totalWeight * "
         "maxQueuedQueries), further capped by tenant.<name>.maxQueued) "
         "instead of the static tenantMaxQueued alone. Stops one hot "
         "tenant's standing queue from monopolising the global queue "
         "budget and burying other tenants' latency behind it; "
         "submissions beyond the shaped cap are rejected with "
         "QueryRejected(reason='tenant_queue_full').")
    .category("scheduler")
    .boolean()
    .create_with_default(True)
)

SCHED_TENANT_SLO_P99_MS = (
    conf("spark.rapids.tpu.scheduler.tenantSloP99Ms")
    .doc("Default per-tenant p99 submit-to-completion latency SLO in "
         "milliseconds, tracked by a sliding-window estimator over the "
         "tenant's recent completions. 0 disables SLO tracking. While "
         "a tenant's observed p99 breaches its target the scheduler "
         "halves that tenant's effective queue cap and sheds the "
         "overflow with QueryRejected(reason='shed_slo') (counted in "
         "tpuq_slo_breach_total, black-box dumped with the dominant "
         "attribution bucket). Per-tenant override: "
         "spark.rapids.tpu.scheduler.tenant.<name>.sloP99Ms.")
    .category("scheduler")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(0)
)

SCHED_SLO_WINDOW = (
    conf("spark.rapids.tpu.scheduler.sloWindow")
    .doc("Sliding-window size (completions per tenant) for the SLO "
         "p99 estimator. Breach detection needs at least 8 samples in "
         "the window, so small windows react faster but gate on fewer "
         "observations.")
    .category("scheduler")
    .integer()
    .check(lambda v: v >= 8, ">= 8")
    .create_with_default(64)
)


# ---------------------------------------------------------------------------
# Cluster-wide tenancy protocol (runtime/tenancy.py + parallel/rendezvous.py)
# ---------------------------------------------------------------------------

TENANCY_ENABLED = (
    conf("spark.rapids.tpu.tenancy.enabled")
    .doc("Cluster-wide tenancy enforcement: each executor's "
         "TenancyAgent piggybacks per-tenant state (in-flight, queued "
         "depth, HBM bytes, largest-runtime query) on its rendezvous "
         "heartbeat, and the coordinator's arbiter fans epoch-tagged "
         "suspend/resume/shed directives back on the heartbeat "
         "response, so a tenant breaching its cluster share on one "
         "executor is preempted even when the starved waiter sits on "
         "another. Requires a rendezvous address and heartbeats "
         "enabled; without them enforcement stays process-local.")
    .category("scheduler")
    .boolean()
    .create_with_default(False)
)

TENANCY_SUSPEND_TTL_MS = (
    conf("spark.rapids.tpu.tenancy.suspendTtlMs")
    .doc("Lease on a remotely-directed suspension: a suspend directive "
         "must be renewed (re-issued by the coordinator on a later "
         "heartbeat) within this long or the token force-resumes "
         "itself — the wedge guard for executor loss / coordinator "
         "restart mid-suspend. 0 derives the TTL as 2x "
         "scheduler.preempt.graceMs.")
    .category("scheduler")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(0)
)

TENANCY_DEGRADED_AFTER = (
    conf("spark.rapids.tpu.tenancy.degradedAfterMisses")
    .doc("After this many consecutive heartbeat failures the "
         "TenancyAgent drops to local-only enforcement (counted in "
         "tpuq_tenancy_degraded_total) until a heartbeat round-trips "
         "again, at which point it re-syncs its suspended-query state "
         "with the (possibly restarted) coordinator.")
    .category("scheduler")
    .integer()
    .check(lambda v: v > 0, "positive")
    .create_with_default(2)
)


# ---------------------------------------------------------------------------
# Result-cache plane (spark_rapids_tpu/cache/, docs/result_cache.md)
# ---------------------------------------------------------------------------

CACHE_ENABLED = (
    conf("spark.rapids.tpu.cache.enabled")
    .doc("Serve repeated queries from the host-resident result cache. "
         "A hit is keyed by sha1(physical-plan fingerprint + "
         "result-affecting confs + input fingerprints) and bypasses "
         "the scheduler and device semaphore entirely; the query log "
         "still records the query with entry['cache'].status='hit'.")
    .category("cache")
    .boolean()
    .create_with_default(False)
)

CACHE_MAX_BYTES = (
    conf("spark.rapids.tpu.cache.maxBytes")
    .doc("Byte budget for resident cached results (Arrow bytes). "
         "Least-recently-used entries are evicted to stay under it; a "
         "single result larger than the budget is never cached.")
    .category("cache")
    .bytes()
    .check(lambda v: v > 0, "positive")
    .create_with_default(256 * 1024 * 1024)
)

CACHE_TTL_MS = (
    conf("spark.rapids.tpu.cache.ttlMs")
    .doc("Time-to-live for cached results in milliseconds; an entry "
         "older than this counts as an eviction at lookup. 0 disables "
         "TTL (entries live until evicted or invalidated).")
    .category("cache")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(600_000)
)

CACHE_MIN_RUNTIME_MS = (
    conf("spark.rapids.tpu.cache.minRuntimeMs")
    .doc("Only cache results whose cold execution took at least this "
         "many milliseconds — sub-millisecond queries churn the byte "
         "budget for no device savings.")
    .category("cache")
    .integer()
    .check(lambda v: v >= 0, "non-negative")
    .create_with_default(0)
)

CACHE_SUBPLAN_ENABLED = (
    conf("spark.rapids.tpu.cache.subplan.enabled")
    .doc("Also cache materialized shuffle-exchange outputs under "
         "subtree signatures, so partially-overlapping queries reuse "
         "shared stages even when their full result keys differ. "
         "Entries share the cache.maxBytes budget.")
    .category("cache")
    .boolean()
    .create_with_default(False)
)


class RapidsConf:
    """Immutable-ish view over a raw key->value dict, validated at init.

    [REF: RapidsConf.scala :: RapidsConf]
    """

    def __init__(self, raw: Optional[Dict[str, Any]] = None):
        self._raw = dict(raw or {})
        self._values: Dict[str, Any] = {}
        unknown = []
        for k, v in self._raw.items():
            e = REGISTRY.entries.get(k)
            if e is None:
                if k.startswith("spark.rapids.sql.expression.") or k.startswith(
                    "spark.rapids.sql.exec."
                ):
                    # per-op kill switches are registered dynamically by the
                    # overrides rule table; store raw
                    self._values[k] = _parse_bool(v)
                elif k.startswith("spark.rapids.tpu.scheduler.tenant."):
                    # per-tenant scheduler overrides (weight/maxInFlight/
                    # maxQueued/hbmShare) keyed by tenant name; the scheduler
                    # parses and validates at tenant creation
                    self._values[k] = v
                elif k.startswith("spark.rapids."):
                    unknown.append(k)
                else:
                    self._values[k] = v
            else:
                self._values[k] = e.convert(v)
        if unknown:
            raise ValueError(f"unknown spark.rapids.* conf keys: {unknown}")

    def get(self, entry: ConfEntry):
        return self._values.get(entry.key, entry.default)

    def get_raw(self, key: str, default=None):
        return self._values.get(key, default)

    def raw_prefix(self, prefix: str) -> Dict[str, Any]:
        """All dynamically-registered raw keys under a prefix (e.g. the
        per-tenant scheduler overrides) — result-key derivation folds
        these in so tenant conf differences key separately."""
        return {k: v for k, v in self._values.items()
                if k.startswith(prefix)}

    def is_op_enabled(self, kind: str, name: str, default: bool = True) -> bool:
        """Per-op kill switch, e.g. spark.rapids.sql.expression.Substring."""
        return self._values.get(f"spark.rapids.sql.{kind}.{name}", default)

    def with_overrides(self, extra: Dict[str, Any]) -> "RapidsConf":
        raw = dict(self._raw)
        raw.update(extra)
        return RapidsConf(raw)

    # convenience properties -------------------------------------------------
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str:
        return str(self.get(EXPLAIN)).upper()

    @property
    def test_enabled(self) -> bool:
        return self.get(TEST_ENABLED)

    @property
    def allowed_non_gpu(self) -> List[str]:
        s = str(self.get(TEST_ALLOWED_NON_GPU)).strip()
        return [x.strip() for x in s.split(",") if x.strip()]

    @property
    def batch_rows(self) -> int:
        return self.get(BATCH_ROWS)

    @property
    def min_bucket_rows(self) -> int:
        return self.get(MIN_BUCKET_ROWS)

    @property
    def shuffle_partitions(self) -> int:
        return self.get(SHUFFLE_PARTITIONS)

    @property
    def shuffle_mode(self) -> str:
        return str(self.get(SHUFFLE_MODE)).upper()

    @property
    def exchange_mode(self) -> str:
        return str(self.get(EXCHANGE_MODE)).lower()

    @property
    def ansi_enabled(self) -> bool:
        return self.get(ANSI_ENABLED)


def generate_configs_md() -> str:
    """Auto-generate docs/configs.md from the registry.

    [REF: RapidsConf.scala :: doc-gen main]
    """
    lines = [
        "# Configuration",
        "",
        "Generated from `spark_rapids_tpu/conf.py` — do not edit by hand.",
        "",
        "| Key | Default | Category | Description |",
        "|---|---|---|---|",
    ]
    for e in sorted(REGISTRY.entries.values(), key=lambda e: e.key):
        if e.internal:
            continue
        lines.append(f"| `{e.key}` | `{e.default}` | {e.category} | {e.doc} |")
    lines.append("")
    return "\n".join(lines)
