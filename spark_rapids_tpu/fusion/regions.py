"""Region selection: greedy maximal chains of fusable map operators.

The pass runs on the CONVERTED plan (after exec rules, transitions and
coalesce insertion), so every surviving node is exactly what would
execute unfused — which is what makes the recorded member signatures
diffable: each member's ``plan_signature`` is computed at its
pre-fusion tree path, the same signature an unfused run of the same
query records, so ``profile diff`` lines fused runs up against unfused
history instead of reporting every member as added/removed.

Selection is structural, not cost-based: a chain is a maximal run of
single-child ``TpuExec`` nodes whose ``fusion()`` hook returns a
(pure fn, cache key) pair.  Everything else is a boundary by
construction — exchanges, joins, aggregates, sorts, limits (stateful
across batches), sample (device-scalar ordinal state), UDF fallbacks
and CPU islands all inherit the default ``fusion() -> None``.  The
``fusion-purity`` lint rule (docs/static_analysis.md) is the static
arm of the same contract: a fusion hook that pulls to the host would
poison every region containing it.
"""

from __future__ import annotations

from typing import List, Tuple

from spark_rapids_tpu.exec.base import ExecNode, TpuExec


def _fusable(node: ExecNode):
    """The node's (fn, key) fusion hook, or None when it must stay a
    region boundary."""
    if not isinstance(node, TpuExec) or len(node.children) != 1:
        return None
    from spark_rapids_tpu.exec.fused import FusedStageExec
    if isinstance(node, FusedStageExec):
        return None  # never re-fuse an already-fused region
    return node.fusion()


def fuse_plan(plan: ExecNode, conf) -> Tuple[ExecNode, int]:
    """Rewrite ``plan`` with FusedStageExec regions; returns
    ``(new_plan, regions_built)``.  No-op (0 regions) unless
    ``spark.rapids.tpu.fusion.enabled`` and mode != off."""
    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu import fusion as F
    from spark_rapids_tpu.exec.fused import FusedStageExec
    from spark_rapids_tpu.runtime.stats import plan_signature

    if not conf.get(C.FUSION_ENABLED):
        return plan, 0
    mode = str(conf.get(C.FUSION_MODE)).lower()
    if mode == "off":
        return plan, 0
    max_ops = int(conf.get(C.FUSION_MAX_OPS))
    min_len = 1 if mode == "aggressive" else 2
    built = 0

    def walk(node: ExecNode, path: str) -> ExecNode:
        nonlocal built
        members: List[TpuExec] = []
        sigs: List[dict] = []
        cur, cur_path = node, path
        while len(members) < max_ops:
            hook = _fusable(cur)
            if hook is None:
                break
            members.append(cur)
            sigs.append({"op": cur.name,
                         "sig": plan_signature(cur.name, cur_path,
                                               cur.schema),
                         "path": cur_path,
                         "key": hook[1]})
            cur = cur.children[0]
            cur_path += ".0"
        if len(members) < min_len:
            node._children = tuple(
                walk(c, f"{path}.{i}")
                for i, c in enumerate(node.children))
            return node
        source = walk(cur, cur_path)
        # one shared source instance: the region pumps it, and the
        # preserved unfused chain (fall-open) bottoms out on it too
        members[-1]._children = (source,)
        region = FusedStageExec(members, sigs, source)
        built += 1
        F.REGIONS_BUILT.inc()
        return region

    return walk(plan, "0"), built
