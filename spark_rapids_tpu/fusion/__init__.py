"""Whole-stage fusion plane: operator chains as single XLA programs.

[REF: sql-plugin/../basicPhysicalOperators.scala :: GpuTieredProject;
 Spark WholeStageCodegenExec]  (PAPER.md §kernels: the reference gets
its single-query throughput from one kernel launch per stage, not one
per operator.)

The exec layer pays a fixed toll at every operator boundary: a pump
dispatch (stats/trace/cancel/prefetch generators), a shape-plane
pad/bucket cycle, a cached-kernel dispatch, and an intermediate
device batch.  For map-shaped operators (project / filter / cast
chains) none of that buys anything — the ops are pure batch→batch
functions that XLA would happily fuse into one program if it ever saw
them together.

This plane makes XLA see them together.  ``fuse_plan`` walks the
converted physical plan after ``apply_overrides`` finishes rewriting
it, greedily stitches maximal chains of unary ``TpuExec`` nodes whose
``fusion()`` hook is non-None into ``FusedStageExec`` regions
(exec/fused.py), and leaves everything else — exchanges, joins,
aggregates, limits, UDF fallbacks, CPU islands — as natural region
boundaries (their ``fusion()`` is None).  Each region compiles to ONE
jitted program through the ``cached_kernel`` chokepoint: intermediate
batches are device-resident SSA values inside the program, and the
pump / pad-mask / shape-bucket boundary runs once per region instead
of once per member.

Conf-gated under ``spark.rapids.tpu.fusion.{enabled,maxOpsPerRegion,
mode}``; a region whose program fails to build or trace falls open to
the preserved unfused chain (counted in ``tpuq_fusion_fallback_total``)
so fusion can never change an answer — only its dispatch count.
See docs/fusion.md.
"""

from __future__ import annotations

from spark_rapids_tpu.runtime.telemetry import REGISTRY

# process-telemetry family (docs/observability.md)
REGIONS_BUILT = REGISTRY.counter(
    "tpuq_fusion_regions_built_total",
    "FusedStageExec regions stitched into plans by the fusion pass")
FALLBACKS = REGISTRY.counter(
    "tpuq_fusion_fallback_total",
    "fused regions that fell open to their unfused pump chain after a "
    "region program failed to build or trace")
COMPILE_SECONDS = REGISTRY.counter(
    "tpuq_fusion_compile_seconds_total",
    "XLA compile seconds attributed to fused region programs (first "
    "dispatch per region signature)")

from spark_rapids_tpu.fusion.regions import fuse_plan  # noqa: E402

__all__ = ["fuse_plan", "REGIONS_BUILT", "FALLBACKS", "COMPILE_SECONDS"]
