"""Per-query wall-clock attribution ledger + flight recorder.

The reference accelerator attributes query wall time through per-op
metrics surfaced in the Spark SQL UI [REF: GpuMetrics.scala; the
qualification/profiling tool's per-stage breakdown]; this engine has
spans (runtime/trace.py), counter deltas (runtime/telemetry.py), and
op stats (runtime/stats.py) — this module is the layer that folds them
into ONE exclusive decomposition that closes against end-to-end wall
time, and that survives a timeout/cancel with evidence.

Three pieces:

* **Ledger** (``attribute``): project every trace span of the query
  onto the single wall-clock timeline and charge each instant to
  exactly one declared bucket (``BUCKETS``).  Overlaps across pump
  threads resolve by specificity (``BUCKET_PRIORITY`` — a semaphore
  wait inside a pump task is a wait, not pump time), so the buckets
  are exclusive by construction, sum to <= e2e, and the gap is
  reported explicitly as ``unaccounted`` — never silently absorbed.
  ``closed`` is the <= ``closeTolerance`` verdict on that gap.

* **Flight recorder** (``FlightRecorder``): a bounded ring of the
  query's most recent spans plus health/retry/cancel events, fed from
  the tracer's span-close path and ``record_event`` — cheap deque
  appends, no new timers.  On a bad exit (timeout, cancel, error,
  health WARN) the ring + ledger dump atomically to
  ``query-<id>.blackbox.json`` (tmp + rename, bounded dir with
  oldest-first eviction), so a query killed at the deadline still
  names its dominant bucket.

* **Verdict engine** (``verdict_line``): one ranked diagnosis line —
  "exchange-bound: 71% of 23.3 s in exchange_collective" — attached to
  the event-log entry, the stats profile, the black box, and rendered
  by ``profile why``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from spark_rapids_tpu.runtime import telemetry as TM

# ---------------------------------------------------------------------------
# The bucket catalog — the declared registry the ledger, the
# bucket-accounting lint rule, and the docs drift gate all read.
# ---------------------------------------------------------------------------

BUCKETS: Dict[str, str] = {
    "queue_wait": "time queued for a QueryServer run slot before "
                  "execution started (server-submitted queries only)",
    "semaphore_wait": "time blocked in the device admission semaphore "
                      "(concurrentGpuTasks) or the pre-materialize hold",
    "compile": "XLA kernel / fused-region / exchange-program compiles "
               "detected on this query's clock",
    "kernel_dispatch": "device compute, H2D/D2H transfer, gather/"
                       "broadcast/concat and other device-batch work",
    "exchange_collective": "ICI exchange collectives (the compiled "
                           "exchange's device launches)",
    "host_shuffle": "host-side shuffle partition/serialize/read/write",
    "spill_io": "device->host->disk spill writes and restore reads",
    "preempted": "time parked in the SUSPENDED state after the "
                 "scheduler preempted the query (permits released, "
                 "residency spilled) — never lands in unaccounted",
    "cache": "result-cache probe and store (serve on hit, put on miss)",
    "pump_idle": "partition-pump machinery between instrumented "
                 "stages: iterator plumbing, batch handoff, "
                 "arrow conversion at the root boundary",
    "host_fallback": "CPU-fallback operator pumps, python UDFs, and "
                     "host-side scans",
    "unaccounted": "e2e wall minus everything above — genuinely "
                   "uninstrumented time, reported, never absorbed",
}

# Verdict label per dominant bucket ("<label>: NN% of S s in <bucket>").
BUCKET_VERDICTS: Dict[str, str] = {
    "queue_wait": "queue-bound",
    "semaphore_wait": "admission-bound",
    "compile": "compile-bound",
    "kernel_dispatch": "kernel-bound",
    "exchange_collective": "exchange-bound",
    "host_shuffle": "shuffle-bound",
    "spill_io": "spill-bound",
    "preempted": "preempt-bound",
    "cache": "cache-bound",
    "pump_idle": "pump-bound",
    "host_fallback": "fallback-bound",
    "unaccounted": "uninstrumented",
}

# Every MetricTimer stage name / pump-stage label in runtime/ + exec/
# must map here (or carry ``# attribution-exempt: <why>``) — enforced
# by the ``bucket-accounting`` lint rule.  "pump" resolves per op at
# fold time: a Cpu* operator's pump is host-fallback, not pump_idle.
STAGE_BUCKETS: Dict[str, Optional[str]] = {
    "pump": "pump_idle",            # Cpu* ops -> host_fallback
    "pumpTask": "pump_idle",
    "opTime": "kernel_dispatch",
    "kernel": "kernel_dispatch",
    "transferTime": "kernel_dispatch",
    "concatTime": "kernel_dispatch",
    "gatherTime": "kernel_dispatch",
    "broadcastTime": "kernel_dispatch",
    "partialTime": "kernel_dispatch",
    "mergeTime": "kernel_dispatch",
    "measureTime": "kernel_dispatch",
    "decideTime": "kernel_dispatch",
    "compile": "compile",
    "collectiveTime": "exchange_collective",
    "partitionTime": "host_shuffle",
    "writeTime": "host_shuffle",
    "readTime": "host_shuffle",
    "udfTime": "host_fallback",
    "scanTime": "host_fallback",
    "spillTime": "spill_io",
    "restoreTime": "spill_io",
    "semaphoreWait": "semaphore_wait",
    "semaphoreWaitTime": "semaphore_wait",
    "preemptWait": "preempted",
    "cacheProbe": "cache",
    "cacheServe": "cache",
    "queueWait": "queue_wait",
    # the query-root span: deliberately NOT charged to any bucket —
    # charging it would absorb every uninstrumented gap and make the
    # closure check vacuous
    "execute": None,
}

# Specificity order for overlap resolution, most specific first: an
# instant covered by several threads' spans charges to the
# highest-priority active bucket.  Waits and one-shot I/O stages beat
# compute; compute beats the pump envelope.
BUCKET_PRIORITY: Tuple[str, ...] = (
    "compile", "preempted", "semaphore_wait", "spill_io",
    "exchange_collective", "host_shuffle", "cache", "host_fallback",
    "kernel_dispatch", "queue_wait", "pump_idle",
)

# closure slack floor: on sub-100ms queries fixed per-query overheads
# (plan metric finalize, log append) dominate any percentage
ABS_CLOSE_SLACK_S = 0.010

_TM_UNACCOUNTED = TM.REGISTRY.counter(
    "tpuq_attribution_unaccounted_seconds_total",
    "per-query wall seconds the attribution ledger could not charge "
    "to any instrumented bucket (the explicit 'unaccounted' gap)")
_TM_DUMPS = TM.REGISTRY.labeled_counter(
    "tpuq_blackbox_dumps_total",
    "flight-recorder black boxes dumped, per trigger "
    "(timeout|cancel|error|health)")


def span_bucket(op: str, stage: str) -> Optional[str]:
    """Bucket of one span; None = uncharged (unknown stage or the
    query-root envelope)."""
    if stage == "pump" and op.startswith("Cpu"):
        return "host_fallback"
    return STAGE_BUCKETS.get(stage)


# ---------------------------------------------------------------------------
# The ledger fold
# ---------------------------------------------------------------------------

def _project(intervals: List[Tuple[float, float, int]],
             t0: float, t1: float) -> List[float]:
    """Charge the [t0, t1] timeline to buckets by priority sweep.

    ``intervals`` is (start, end, priority_index); returns seconds per
    ``BUCKET_PRIORITY`` index.  At each elementary segment between
    boundary points the highest-priority active bucket (lowest index)
    wins, so the result is exclusive by construction and sums to at
    most (t1 - t0)."""
    n = len(BUCKET_PRIORITY)
    out = [0.0] * n
    if t1 <= t0 or not intervals:
        return out
    events: List[Tuple[float, int, int]] = []
    for s, e, pri in intervals:
        s, e = max(s, t0), min(e, t1)
        if e > s:
            events.append((s, 1, pri))
            events.append((e, -1, pri))
    if not events:
        return out
    events.sort(key=lambda ev: ev[0])
    active = [0] * n
    prev = events[0][0]
    i = 0
    while i < len(events):
        t = events[i][0]
        if t > prev:
            for pri in range(n):
                if active[pri]:
                    out[pri] += t - prev
                    break
            prev = t
        while i < len(events) and events[i][0] == t:
            active[events[i][2]] += events[i][1]
            i += 1
    return out


def attribute(tracer=None, spans: Optional[Iterable] = None,
              e2e_s: Optional[float] = None,
              tolerance: float = 0.10,
              extras: Optional[Dict[str, float]] = None
              ) -> Dict[str, Any]:
    """Fold a query's trace spans into the exclusive bucket ledger.

    ``tracer`` is a finished ``trace.Tracer`` (preferred — its
    ``t_start``/``wall_s`` anchor the timeline); ``spans`` + ``e2e_s``
    is the raw form the black-box/test path uses.  ``extras`` adds
    scalar seconds measured outside the trace window (the server's
    queue wait) — they extend e2e rather than competing for it.

    Returns ``{"buckets", "e2e_s", "unaccounted_s", "closed",
    "tolerance", "verdict", "dominant", "dominant_share"}`` with
    buckets rounded, exclusive, and summing (with ``unaccounted``) to
    ``e2e_s`` exactly."""
    if tracer is not None:
        spans = list(tracer.events)
        t0 = tracer.t_start
        wall = tracer.wall_s
        if wall is None:
            wall = (time.perf_counter() - t0)
        t1 = t0 + wall
    else:
        spans = list(spans or ())
        if spans:
            t0 = min(sp.t0 for sp in spans)
            t1 = max(sp.t1 for sp in spans)
        else:
            t0 = t1 = 0.0
        if e2e_s is not None:
            t1 = t0 + e2e_s
    e2e = max(t1 - t0, 0.0)
    pri_index = {b: i for i, b in enumerate(BUCKET_PRIORITY)}
    intervals: List[Tuple[float, float, int]] = []
    for sp in spans:
        b = span_bucket(sp.op, sp.stage)
        if b is None:
            continue
        intervals.append((sp.t0, sp.t1, pri_index[b]))
    per_pri = _project(intervals, t0, t1)
    buckets = {b: per_pri[i] for i, b in enumerate(BUCKET_PRIORITY)}
    covered = sum(per_pri)
    unaccounted = max(e2e - covered, 0.0)
    for name, secs in (extras or {}).items():
        if name in buckets and secs:
            buckets[name] += float(secs)
            e2e += float(secs)
    buckets["unaccounted"] = unaccounted
    tol = float(tolerance)
    closed = unaccounted <= max(tol * e2e, ABS_CLOSE_SLACK_S)
    ranked = sorted(buckets.items(), key=lambda kv: -kv[1])
    dominant, dom_s = ranked[0] if ranked else ("unaccounted", 0.0)
    share = (dom_s / e2e) if e2e > 0 else 0.0
    att = {
        "buckets": {b: round(s, 6) for b, s in buckets.items()},
        "e2e_s": round(e2e, 6),
        "unaccounted_s": round(unaccounted, 6),
        "closed": closed,
        "tolerance": tol,
        "dominant": dominant,
        "dominant_share": round(share, 4),
    }
    att["verdict"] = verdict_line(att)
    return att


def verdict_line(att: Dict[str, Any]) -> str:
    """The one-line diagnosis: '<label>: NN% of S s in <bucket>'."""
    dom = att.get("dominant") or "unaccounted"
    label = BUCKET_VERDICTS.get(dom, dom)
    share = float(att.get("dominant_share") or 0.0)
    e2e = float(att.get("e2e_s") or 0.0)
    line = f"{label}: {share:.0%} of {e2e:.1f} s in {dom}"
    if not att.get("closed", True):
        gap = float(att.get("unaccounted_s") or 0.0)
        line += f" (NOT CLOSED: {gap:.1f} s unaccounted)"
    return line


# ---------------------------------------------------------------------------
# Flight recorder — one query at a time owns it (trace._ACTIVE model)
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded ring of a query's most recent spans + health/retry/
    cancel events.  Appends are lock-free deque pushes (deque.append
    is atomic) — the black box is cheap enough to leave on by
    default."""

    def __init__(self, query_id: int, ring_size: int = 256):
        self.query_id = query_id
        self.ring_size = max(8, int(ring_size))
        self.t_start = time.perf_counter()
        self.spans: deque = deque(maxlen=self.ring_size)
        self.events: deque = deque(maxlen=self.ring_size)

    # called from Tracer.end via the duck-typed ``recorder`` hook —
    # keep it to one append
    def record_span(self, span) -> None:
        self.spans.append((span.op, span.stage,
                           span.t0 - self.t_start, span.t1 - span.t0))

    def record_event(self, kind: str, payload: dict) -> None:
        self.events.append({
            "kind": kind,
            "t_s": round(time.perf_counter() - self.t_start, 6),
            **payload})

    def snapshot(self) -> Dict[str, Any]:
        return {
            "query_id": self.query_id,
            "ring_size": self.ring_size,
            "recent_spans": [
                {"op": op, "stage": stage, "t_s": round(t, 6),
                 "dur_s": round(d, 6)}
                for op, stage, t, d in list(self.spans)],
            "events": list(self.events),
        }


_ACTIVE: Optional[FlightRecorder] = None
_ACTIVE_LOCK = threading.Lock()


def current() -> Optional[FlightRecorder]:
    return _ACTIVE


def start_query(query_id: int,
                ring_size: int = 256) -> Optional[FlightRecorder]:
    """Install a fresh recorder; None when another query owns it (a
    nested execution rides the owner, same as tracing)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            return None
        _ACTIVE = FlightRecorder(query_id, ring_size=ring_size)
        return _ACTIVE


def end_query(rec: Optional[FlightRecorder]) -> None:
    global _ACTIVE
    if rec is None:
        return
    with _ACTIVE_LOCK:
        if _ACTIVE is rec:
            _ACTIVE = None


def record_event(kind: str, payload: dict) -> None:
    """Event into the active query's ring, no-op otherwise — THE hook
    free-standing producers (retry policy, health evaluator, cancel
    path) use without carrying a recorder reference."""
    rec = _ACTIVE
    if rec is not None:
        rec.record_event(kind, payload)


# ---------------------------------------------------------------------------
# Black-box dumps — atomic, bounded, concurrent-safe
# ---------------------------------------------------------------------------

def blackbox_path(dir_path: str, query_id: int) -> str:
    return os.path.join(dir_path, f"query-{query_id:06d}.blackbox.json")


def _evict_oldest(dir_path: str, max_dumps: int) -> None:
    """Keep at most ``max_dumps`` black boxes, oldest-first eviction by
    mtime — a crash-looping server must never flood the dump dir."""
    try:
        names = [n for n in os.listdir(dir_path)
                 if n.endswith(".blackbox.json")]
        if len(names) <= max_dumps:
            return
        full = [os.path.join(dir_path, n) for n in names]
        full.sort(key=lambda p: (os.path.getmtime(p), p))
        for p in full[:len(full) - max_dumps]:
            try:
                os.unlink(p)
            except OSError:
                pass
    except OSError:
        pass


def dump_blackbox(dir_path: str, query_id: int, trigger: str,
                  attribution: Optional[Dict[str, Any]] = None,
                  recorder: Optional[FlightRecorder] = None,
                  extra: Optional[Dict[str, Any]] = None,
                  max_dumps: int = 64) -> Optional[str]:
    """Atomically write ``query-<id>.blackbox.json``.

    tmp + ``os.replace`` in the spill-file style (runtime/memory.py,
    telemetry's prom dump): a reader never sees a torn file and a
    mid-dump crash leaves only a uniquely-named tmp, not a corrupt
    dump.  The tmp name carries pid + random hex so concurrent
    QueryServer queries dumping into one dir never collide.  Returns
    the path, None on failure (observability never fails the query)."""
    import sys
    box = {
        "record": "blackbox",
        "query_id": query_id,
        "trigger": trigger,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    if attribution is not None:
        box["attribution"] = attribution
        box["verdict"] = attribution.get("verdict")
    if recorder is not None:
        box["flight_recorder"] = recorder.snapshot()
    if extra:
        box.update(extra)
    try:
        os.makedirs(dir_path, exist_ok=True)
        final = blackbox_path(dir_path, query_id)
        tmp = os.path.join(
            dir_path,
            f".{os.path.basename(final)}.tmp-{os.getpid()}-"
            f"{uuid.uuid4().hex[:8]}")
        with open(tmp, "w") as f:
            json.dump(box, f, default=str)
        os.replace(tmp, final)
        _TM_DUMPS.inc(trigger)
        _evict_oldest(dir_path, max_dumps)
        return final
    except OSError as e:
        print(f"[tpuq] blackbox dump failed: {e}", file=sys.stderr,
              flush=True)
        return None


def note_unaccounted(seconds: float) -> None:
    if seconds > 0:
        _TM_UNACCOUNTED.inc(seconds)
