"""HBM budget arbiter + spill store + OOM-retry framework.

[REF: sql-plugin/../GpuDeviceManager.scala, spill/SpillFramework.scala,
 RmmRapidsRetryIterator.scala :: withRetry / withRetryNoSplit /
 splitSpillableInHalfByRows; spark-rapids-jni :: RmmSpark (per-thread OOM
 state machine, forceRetryOOM injection)]

TPU re-design: there is no RMM — XLA/PJRT owns HBM — so the arbiter is an
*accounting* layer ABOVE the runtime (SURVEY §2.2 N10/N12): operators
``reserve()`` bytes before materializing batches; registered
``SpillableBatch``es are the reclaim pool.  When a reservation would
exceed the budget the arbiter synchronously spills victims
device→host→disk (host tier capped by
``spark.rapids.memory.host.spillStorageSize``, disk tier under
``spark.rapids.tpu.spillPath``), and if still short raises ``RetryOOM``
for ``with_retry`` to catch: restore-from-checkpoint, halve the input by
rows (``SplitAndRetryOOM``), re-run the closure per half.

The ``injectOomAtAlloc`` conf forces an OOM at the Nth reservation — the
test hook that makes the retry/spill path deterministically coverable
(the RmmSpark.forceRetryOOM analog, SURVEY §4.2).
"""

from __future__ import annotations

import atexit
import contextlib
import os
import shutil
import threading
import uuid
import zlib
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import DeviceBatch, DeviceColumn
from spark_rapids_tpu.runtime import cancel
from spark_rapids_tpu.runtime import resilience as R
from spark_rapids_tpu.runtime import telemetry as TM
from spark_rapids_tpu.runtime import trace

# process-cumulative counters (per-manager views live in mgr.metrics);
# gauges pull the CURRENT manager's state at snapshot time
_TM_RESERVE = TM.REGISTRY.counter(
    "tpuq_hbm_reserve_bytes_total",
    "bytes reserved against the HBM budget (cumulative)")
_TM_SPILL_HOST = TM.REGISTRY.counter(
    "tpuq_spill_host_bytes_total", "device→host spill bytes")
_TM_SPILL_DISK = TM.REGISTRY.counter(
    "tpuq_spill_disk_bytes_total", "host→disk spill bytes")
_TM_RESTORE = TM.REGISTRY.counter(
    "tpuq_restore_bytes_total",
    "bytes restored to device from the host/disk spill tiers")
_TM_RETRY_OOM = TM.REGISTRY.counter(
    "tpuq_retry_oom_total", "RetryOOM raises (incl. injected)")
_TM_SPLIT_RETRY = TM.REGISTRY.counter(
    "tpuq_split_retry_total", "SplitAndRetryOOM batch halvings")
_TM_PREEMPT_SPILLED = TM.REGISTRY.counter(
    "tpuq_preempt_spilled_bytes_total",
    "device bytes spilled to host because their query was suspended "
    "by the preemption plane")
_TM_TENANT_BREACH = TM.REGISTRY.labeled_counter(
    "tpuq_tenant_hbm_breach_total",
    "reservations denied because the tenant's enforced HBM byte "
    "budget (hbmShare x pool) was exhausted even after spilling its "
    "own residency", label="tenant")


class RetryOOM(RuntimeError):
    """Device memory exhausted; caller should free/spill and re-run."""


class SplitAndRetryOOM(RetryOOM):
    """Re-running whole won't fit; caller must halve the input."""


# ---------------------------------------------------------------------------
# spill-file integrity + per-process spill directory lifetime
# ---------------------------------------------------------------------------

def _file_crc32(path: str) -> int:
    """CRC32 of a file's bytes, chunked (spill files can be large)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def _write_crc_sidecar(path: str) -> None:
    with open(path + ".crc32", "w") as f:
        f.write(f"{_file_crc32(path):08x}\n")


def _verify_crc_sidecar(path: str) -> None:
    """Raise ``ValueError`` (spill_read-retryable, domain-tagged on
    exhaustion) when the payload no longer matches its recorded CRC —
    a garbled batch must never restore silently."""
    sidecar = path + ".crc32"
    if not os.path.exists(sidecar):
        return  # pre-integrity spill file; np.load is the only check
    with open(sidecar) as f:
        want = int(f.read().strip(), 16)
    got = _file_crc32(path)
    if got != want:
        raise ValueError(
            f"spill file {path} corrupt: crc32 {got:08x} != "
            f"recorded {want:08x}")


def _unlink_spill(path: str) -> None:
    for p in (path, path + ".crc32"):
        if os.path.exists(p):
            os.unlink(p)


# every per-process spill subdirectory ever handed to a manager in this
# process; one atexit hook removes them all, so a normal exit strands
# no orphan .npz files under the shared spillPath root
_SPILL_DIRS: set = set()
_SPILL_DIRS_LOCK = threading.Lock()


def _cleanup_spill_dirs() -> None:
    with _SPILL_DIRS_LOCK:
        dirs = list(_SPILL_DIRS)
        _SPILL_DIRS.clear()
    for d in dirs:
        shutil.rmtree(d, ignore_errors=True)


def _register_spill_dir(path: str) -> None:
    with _SPILL_DIRS_LOCK:
        if not _SPILL_DIRS:
            atexit.register(_cleanup_spill_dirs)
        _SPILL_DIRS.add(path)


class SpillableBatch:
    """A device batch registered with the arbiter as reclaimable.

    States: device (batch live, bytes counted) → host (numpy copies) →
    disk (one .npz under spillPath).  ``get()`` restores to device,
    re-reserving its bytes.  [REF: SpillableColumnarBatch]
    """

    def __init__(self, batch: DeviceBatch, manager: "DeviceMemoryManager",
                 reserve: bool = True):
        self._mgr = manager
        self._batch: Optional[DeviceBatch] = batch
        self._host: Optional[list] = None
        self._disk_path: Optional[str] = None
        # True only while this batch's host copy is counted in the
        # manager's _host_used (a disk restore staged in _host is NOT)
        self._host_accounted = False
        # True while the device bytes are counted in _reserved —
        # reserve=False registrations (e.g. out-of-core slices carved
        # from already-materialized inputs) must not release bytes they
        # never claimed
        self._device_accounted = reserve
        # set when a disk spill degraded (stayed in the host tier); the
        # host-limit eviction loop must skip such victims or it spins
        self._disk_spill_failed = False
        # True while a disk write is in flight for this batch.  The
        # write's retry/backoff sleeps are preempt yield points, and a
        # park's suspend-spill can re-enter the host-eviction loop on
        # this very batch — without the guard both frames write a file
        # and the second assignment orphans the first.
        self._disk_spilling = False
        self.schema = batch.schema
        self.compacted = batch.compacted
        self.nbytes = batch.nbytes()
        # static row capacity, readable without restoring a spilled
        # batch (the join's skew re-check must not force an unspill)
        self.capacity = batch.capacity
        # tenancy: the batch belongs to the ambient query — its bytes
        # charge that tenant's enforced HBM budget, and a suspend of
        # that query spills it through the tiers
        tok = cancel.current()
        self._tenant = tok.tenant if tok is not None else "default"
        self._query_id = tok.query_id if tok is not None else None
        if reserve:
            manager.reserve(self.nbytes, tenant=self._tenant)
        manager._register(self)

    @property
    def tier(self) -> str:
        if self._batch is not None:
            return "device"
        if self._host is not None:
            return "host"
        return "disk"

    def spill_to_host(self) -> int:
        """Device → host.  Returns bytes freed on device."""
        if self._batch is None:
            return 0
        with trace.span("Spill", "spillTime"):
            return self._spill_to_host()

    def _spill_to_host(self) -> int:
        import jax
        b = self._batch
        leaves, treedef = jax.tree.flatten(b)
        # one overlapped transfer round trip (see columnar.device_to_host)
        from spark_rapids_tpu.shims import get_shim
        shim = get_shim()
        for x in leaves:
            shim.async_copy_to_host(x)
        self._host = ([np.asarray(x) for x in leaves], treedef)
        self._batch = None
        self._host_accounted = True
        was_accounted = self._device_accounted
        self._device_accounted = False
        self._mgr._on_spill(self, self.nbytes,
                            release_device=was_accounted)
        return self.nbytes

    def spill_to_disk(self) -> int:
        """Host → disk through the ``spill_write`` failure domain.
        Returns host bytes freed (0 when the write degraded — the batch
        stays in the host tier, marked so the eviction loop skips it)."""
        if self._host is None or self._disk_spilling:
            return 0
        with trace.span("Spill", "spillTime"):
            return self._spill_to_disk()

    def _spill_to_disk(self) -> int:
        leaves, treedef = self._host
        os.makedirs(self._mgr.spill_path, exist_ok=True)
        if self._disk_path is not None:
            # a restore raced an eviction (preemption churn makes this
            # reachable: the restore staged _host, then RetryOOM'd its
            # reservation while the evictor re-spilled) — drop the
            # stale file or the overwrite below orphans it
            _unlink_spill(self._disk_path)
            self._disk_path = None
        path = os.path.join(self._mgr.spill_path,
                            f"spill-{uuid.uuid4().hex}.npz")

        def attempt():
            R.INJECTOR.on("spill_write")
            np.savez(path, *leaves)
            # integrity sidecar: restore refuses a payload whose bytes
            # no longer match what was written
            _write_crc_sidecar(path)
            return True

        def degrade():
            return False  # keep the host copy; data is still safe

        self._disk_spilling = True
        try:
            ok = R.run_guarded("spill_write", attempt, op="spill_to_disk",
                               degrade=degrade)
        finally:
            self._disk_spilling = False
        if not ok:
            self._disk_spill_failed = True
            _unlink_spill(path)  # drop any partial file
            return 0
        if self._disk_path is not None and self._disk_path != path:
            # someone re-spilled this batch while our write was in its
            # retry loop — never orphan their file
            _unlink_spill(self._disk_path)
        self._disk_path = path
        self._treedef = treedef
        freed = sum(x.nbytes for x in leaves)
        self._host = None
        if self._host_accounted:
            with self._mgr._lock:
                self._mgr._host_used = max(
                    0, self._mgr._host_used - freed)
            self._host_accounted = False
        self._mgr._on_disk_spill(self, freed)
        return freed

    def get(self) -> DeviceBatch:
        """Restore (if needed) and return the device batch."""
        if self._batch is not None:
            return self._batch
        with trace.span("Spill", "restoreTime"):
            return self._restore()

    def _restore(self) -> DeviceBatch:
        import jax
        from_host = self._host is not None
        if not from_host and self._disk_path is not None:
            # disk staging never touches _host_used accounting.  The
            # restore passes the ``spill_read`` failure domain: IO
            # faults (missing/corrupt .npz) retry, and exhaustion is a
            # domain-tagged terminal error — the data is gone, there is
            # no host path to degrade to.
            def attempt():
                R.INJECTOR.on("spill_read")
                _verify_crc_sidecar(self._disk_path)
                with np.load(self._disk_path) as z:
                    return [z[k] for k in z.files]

            leaves = R.run_guarded("spill_read", attempt,
                                   op="spill_restore")
            self._host = (leaves, self._treedef)
            _unlink_spill(self._disk_path)
            self._disk_path = None
        leaves, treedef = self._host
        self._mgr.reserve(self.nbytes, _restoring=self,
                          tenant=self._tenant)
        self._device_accounted = True
        self._batch = jax.tree.unflatten(
            treedef, [jax.numpy.asarray(x) for x in leaves])
        self._host = None
        self._mgr.metrics["restoredBytes"] += self.nbytes
        _TM_RESTORE.inc(self.nbytes)
        if from_host and self._host_accounted:
            self._host_accounted = False
            self._mgr._on_restore(self)
        return self._batch

    def close(self):
        self._mgr._unregister(self)
        if self._disk_path is not None:
            _unlink_spill(self._disk_path)
            self._disk_path = None
        self._batch = None
        self._host = None


class DeviceMemoryManager:
    """The budget arbiter [REF: GpuDeviceManager + SpillFramework].

    Budget = ``poolSize`` if set, else ``allocFraction`` × detected HBM
    (PJRT ``memory_stats().bytes_limit``; 4 GiB fallback when the
    platform doesn't report, e.g. the virtual CPU mesh).
    """

    def __init__(self, budget: Optional[int] = None,
                 alloc_fraction: float = 0.85,
                 host_limit: int = 4 << 30,
                 spill_path: str = "/tmp/tpuq-spill",
                 inject_oom_at: int = -1,
                 retry_max_attempts: int = 8,
                 debug: bool = False,
                 conf=None):
        self.retry_max_attempts = retry_max_attempts
        self._lock = threading.RLock()
        self._spillables: Dict[int, SpillableBatch] = {}
        # per-tenant HBM enforcement: live reserved bytes per tenant,
        # checked against hbmShare x budget at every reserve.  The conf
        # is kept only to resolve per-tenant hbmShare overrides.
        self._conf = conf
        self._tenant_used: Dict[str, int] = {}
        self._tenant_share_default = 1.0
        if conf is not None:
            from spark_rapids_tpu import conf as C
            self._tenant_share_default = float(
                conf.get(C.SCHED_TENANT_HBM_SHARE))
        # leak tracker [REF: cudf MemoryCleaner]: with debug on, every
        # registration records its creation stack; unreleased handles
        # are reported at shutdown / replacement (LEAK DETECTED)
        self.debug = debug
        self._origins: Dict[int, str] = {}
        if debug:
            import atexit
            atexit.register(self.report_leaks)
        self._reserved = 0
        self._host_used = 0
        self.host_limit = host_limit
        # each manager spills into its own per-process subdirectory of
        # the configured root — concurrent/killed processes sharing one
        # spillPath can no longer collide, and the atexit hook removes
        # the whole subtree on normal exit
        self.spill_root = spill_path
        self.spill_path = os.path.join(
            spill_path, f"proc-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        _register_spill_dir(self.spill_path)
        self._alloc_count = 0
        self._inject_at = inject_oom_at
        self.metrics = {"spillToHostBytes": 0, "spillToDiskBytes": 0,
                        "restoredBytes": 0, "retryOOMs": 0,
                        "splitRetries": 0, "peakReserved": 0,
                        "tenantBreaches": 0, "preemptSpilledBytes": 0}
        self.budget = budget if budget else self._detect_budget(
            alloc_fraction)

    @staticmethod
    def _detect_budget(fraction: float) -> int:
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats()
            if stats and stats.get("bytes_limit"):
                return int(stats["bytes_limit"] * fraction)
        except Exception:
            pass
        return int((4 << 30) * fraction)

    # -- accounting ---------------------------------------------------------
    def reserve(self, nbytes: int, _restoring=None,
                tenant: Optional[str] = None) -> None:
        """Claim bytes for an upcoming materialization, charged to
        ``tenant`` (the ambient query token's tenant when omitted).
        Synchronously spills victims if needed; raises RetryOOM when
        the global budget — or the tenant's enforced hbmShare byte
        budget — cannot be met (or when fault injection fires).  A
        tenant breach escalates OUTSIDE the manager lock: spill the
        tenant's own residency first, then ask the scheduler to
        preempt its largest-runtime other query, then RetryOOM."""
        if tenant is None:
            tok = cancel.current()
            tenant = tok.tenant if tok is not None else "default"
        breached = False
        with self._lock:
            self._alloc_count += 1
            if self._inject_at >= 0 and self._alloc_count == self._inject_at:
                self.metrics["retryOOMs"] += 1
                _TM_RETRY_OOM.inc()
                raise RetryOOM(
                    f"injected OOM at allocation {self._alloc_count}")
            if R.INJECTOR.armed:
                # the ``alloc`` failure domain: an injected fault here
                # IS a forced OOM — it re-enters the existing
                # RetryOOM/with_retry rollback machinery rather than a
                # separate retry loop
                try:
                    R.INJECTOR.on("alloc")
                except R.InjectedDeviceError as e:
                    self.metrics["retryOOMs"] += 1
                    _TM_RETRY_OOM.inc()
                    raise RetryOOM(str(e)) from e
            if nbytes > self.budget:
                self.metrics["retryOOMs"] += 1
                _TM_RETRY_OOM.inc()
                raise SplitAndRetryOOM(
                    f"allocation of {nbytes} B exceeds the whole budget "
                    f"({self.budget} B) — split required")
            while self._reserved + nbytes > self.budget:
                if not self._spill_one(exclude=_restoring):
                    self.metrics["retryOOMs"] += 1
                    _TM_RETRY_OOM.inc()
                    raise RetryOOM(
                        f"cannot reserve {nbytes} B: {self._reserved} of "
                        f"{self.budget} B reserved, nothing left to spill")
            tenant_budget = self._tenant_budget(tenant)
            if tenant_budget < self.budget:
                # spill-first: the tenant's own device residency pays
                # before anyone else is disturbed
                while (self._tenant_used.get(tenant, 0) + nbytes
                       > tenant_budget):
                    if not self._spill_one_tenant(tenant,
                                                  exclude=_restoring):
                        break
                if (self._tenant_used.get(tenant, 0) + nbytes
                        > tenant_budget):
                    breached = True
                    self.metrics["tenantBreaches"] += 1
                    self.metrics["retryOOMs"] += 1
            if not breached:
                self._reserved += nbytes
                self._tenant_used[tenant] = (
                    self._tenant_used.get(tenant, 0) + nbytes)
                _TM_RESERVE.inc(nbytes)
                self.metrics["peakReserved"] = max(
                    self.metrics["peakReserved"], self._reserved)
        if breached:
            _TM_TENANT_BREACH.inc(tenant)
            _TM_RETRY_OOM.inc()
            # escalate to preemption: suspend the tenant's largest-
            # runtime OTHER running query so its reservations unwind.
            # Must run without the manager lock — the scheduler takes
            # its own lock and the documented order is sched -> memory.
            tok = cancel.current()
            exclude = tok.query_id if tok is not None else None
            from spark_rapids_tpu.runtime import scheduler
            sched = scheduler.peek_scheduler()
            preempted = False
            if sched is not None:
                try:
                    preempted = sched.request_tenant_preemption(
                        tenant, exclude_query_id=exclude)
                except Exception:
                    pass  # best-effort; the RetryOOM still rolls back
            if not preempted:
                # no local victim — relay to the cluster arbiter so it
                # can suspend the tenant's largest query on ANOTHER
                # executor (piggybacks on the next heartbeat)
                from spark_rapids_tpu.runtime import tenancy
                agent = tenancy.peek_agent()
                if agent is not None:
                    try:
                        agent.notify_breach(tenant)
                    except Exception:
                        pass
            raise RetryOOM(
                f"tenant {tenant} cannot reserve {nbytes} B: "
                f"{self._tenant_used.get(tenant, 0)} of its "
                f"{self._tenant_budget(tenant)} B hbmShare budget used "
                "and its own residency is already spilled")

    def release(self, nbytes: int, tenant: Optional[str] = None) -> None:
        if tenant is None:
            tok = cancel.current()
            tenant = tok.tenant if tok is not None else "default"
        with self._lock:
            self._reserved = max(0, self._reserved - nbytes)
            if tenant in self._tenant_used:
                self._tenant_used[tenant] = max(
                    0, self._tenant_used[tenant] - nbytes)

    def _tenant_budget(self, tenant: str) -> int:
        """The tenant's enforced HBM byte budget: hbmShare (per-tenant
        conf override, else the scheduler-wide default) x pool."""
        share = self._tenant_share_default
        if self._conf is not None:
            raw = self._conf.get_raw(
                f"spark.rapids.tpu.scheduler.tenant.{tenant}.hbmShare")
            if raw is not None:
                try:
                    share = float(raw)
                except (TypeError, ValueError):
                    pass
        return int(min(1.0, max(0.0, share)) * self.budget)

    def _spill_one_tenant(self, tenant: str, exclude=None) -> bool:
        for s in list(self._spillables.values()):
            if (s is exclude or s.tier != "device"
                    or not s._device_accounted or s._tenant != tenant):
                continue
            s.spill_to_host()
            return True
        return False

    def tenant_usage(self) -> Dict[str, int]:
        """Live reserved bytes per tenant (snapshot)."""
        with self._lock:
            return dict(self._tenant_used)

    @contextlib.contextmanager
    def transient(self, nbytes: int):
        """Reserve for the duration of a device op (operator working-set
        accounting; released on exit)."""
        self.reserve(nbytes)
        try:
            yield
        finally:
            self.release(nbytes)

    def _spill_one(self, exclude=None) -> bool:
        # oldest-registered first (approximate LRU)
        for s in list(self._spillables.values()):
            if s is exclude or s.tier != "device":
                continue
            s.spill_to_host()
            return True
        return False

    # -- spillable registry callbacks --------------------------------------
    def _register(self, s: SpillableBatch) -> None:
        with self._lock:
            self._spillables[id(s)] = s
            if self.debug:
                import traceback
                self._origins[id(s)] = "".join(
                    traceback.format_stack(limit=12)[:-2])

    def leaked(self, include_pinned: bool = False) -> List[tuple]:
        """(batch, origin-stack) for every never-closed registration.
        The scan cache is a deliberate long-lived pool — excluded unless
        ``include_pinned`` (its entries close on eviction)."""
        from spark_rapids_tpu.exec.basic import _scan_cache
        pinned = {id(sp) for entries in _scan_cache.values()
                  for pairs in entries.values() for sp, _ in pairs}
        with self._lock:
            return [(s, self._origins.get(i, "<enable memory.gpu.debug "
                                             "for stacks>"))
                    for i, s in self._spillables.items()
                    if include_pinned or i not in pinned]

    def spill_pressure(self) -> float:
        """Occupancy fraction of the HOST spill tier (0.0 = empty,
        >= 1.0 = the next host spill will push victims to disk).  The
        admission controller sheds new queries when this crosses its
        watermark — BEFORE the arbiter starts thrashing the disk tier."""
        if self.host_limit <= 0:
            return 0.0
        return self._host_used / self.host_limit

    def report_leaks(self) -> int:
        leaks = self.leaked()
        for s, origin in leaks:
            print(f"LEAK DETECTED: spillable batch {s.nbytes} B "
                  f"(tier={s.tier}) never closed; created at:\n{origin}")
        return len(leaks)

    def suspend_spill(self, query_id: int) -> int:
        """Spill a suspending query's device-resident registered
        batches to the host tier so the preemptor inherits its HBM
        headroom (scan-cache pins are shared residency — they stay).
        Called by the first thread to park in ``_park_suspended``;
        the batches rehydrate lazily (CRC-checked, bit-identical) when
        the resumed query next touches them.  Returns bytes spilled."""
        from spark_rapids_tpu.exec.basic import _scan_cache
        pinned = {id(sp) for entries in _scan_cache.values()
                  for pairs in entries.values() for sp, _ in pairs}
        spilled = 0
        with self._lock:
            for s in list(self._spillables.values()):
                if (s.tier != "device" or id(s) in pinned
                        or s._query_id != query_id):
                    continue
                spilled += s.spill_to_host()
        if spilled:
            self.metrics["preemptSpilledBytes"] += spilled
            _TM_PREEMPT_SPILLED.inc(spilled)
        return spilled

    def reclaim_all(self) -> int:
        """Close every non-pinned registered spillable — the cancelled
        query's reclamation sweep.  Closing releases device/host
        accounting and unlinks disk spill files (+ CRC sidecars), so
        ``report_leaks()`` returns 0 afterwards.  Returns the number of
        batches reclaimed."""
        n = 0
        for s, _origin in self.leaked():
            s.close()
            n += 1
        return n

    def _unregister(self, s: SpillableBatch) -> None:
        with self._lock:
            self._spillables.pop(id(s), None)
            self._origins.pop(id(s), None)
            if s.tier == "device" and s._device_accounted:
                s._device_accounted = False
                self.release(s.nbytes, tenant=s._tenant)
            elif s._host_accounted:
                # symmetric with _on_spill: host-tier bytes leave the
                # host budget when the batch is closed/evicted (staged
                # disk restores were never counted — skip those)
                s._host_accounted = False
                self._host_used = max(0, self._host_used - s.nbytes)

    def _on_spill(self, s: SpillableBatch, nbytes: int,
                  release_device: bool = True) -> None:
        with self._lock:
            if release_device:
                # charge the batch's OWN tenant, not the ambient one —
                # the global spill loop may evict another query's batch
                self.release(nbytes, tenant=s._tenant)
            self._host_used += nbytes
            self.metrics["spillToHostBytes"] += nbytes
            _TM_SPILL_HOST.inc(nbytes)
            while self._host_used > self.host_limit:
                victim = next(
                    (v for v in self._spillables.values()
                     if v.tier == "host" and v._host_accounted
                     and not v._disk_spill_failed
                     and not v._disk_spilling and v is not s), None)
                if victim is None:
                    break
                victim.spill_to_disk()  # decrements _host_used itself

    def _on_disk_spill(self, s: SpillableBatch, nbytes: int) -> None:
        self.metrics["spillToDiskBytes"] += nbytes
        _TM_SPILL_DISK.inc(nbytes)

    def _on_restore(self, s: SpillableBatch) -> None:
        with self._lock:
            self._host_used = max(0, self._host_used - s.nbytes)


# ---------------------------------------------------------------------------
# process-wide manager, configured per session conf
# ---------------------------------------------------------------------------

_manager: Optional[DeviceMemoryManager] = None
_manager_lock = threading.Lock()


def get_manager(conf=None) -> DeviceMemoryManager:
    """The process arbiter.  First caller's conf wins; a session with
    explicit memory confs replaces an unconfigured default."""
    global _manager
    replaced = False
    with _manager_lock:
        if _manager is None:
            _manager = _build(conf)
        elif conf is not None:
            cfg = _build(conf)
            if (cfg.budget, cfg.host_limit, cfg._inject_at,
                    cfg.retry_max_attempts, cfg.spill_root,
                    cfg.debug) != (
                    _manager.budget, _manager.host_limit,
                    _manager._inject_at, _manager.retry_max_attempts,
                    _manager.spill_root, _manager.debug):
                _manager = cfg
                replaced = True
        mgr = _manager
    if replaced:
        # a new manager orphans batches registered with the old one —
        # evict the device-resident scan cache so nothing keeps
        # accounting against the dead arbiter.  Outside _manager_lock:
        # eviction takes the scan-cache lock (tier 0) and each close
        # talks to its own batch's arbiter, never the module global.
        from spark_rapids_tpu.exec.basic import clear_scan_cache
        clear_scan_cache()
    return mgr


def peek_manager() -> Optional[DeviceMemoryManager]:
    """The process arbiter if one exists — never creates (the cancel
    reclamation path must not instantiate state as a side effect)."""
    return _manager


def reset_manager() -> None:
    global _manager
    with _manager_lock:
        _manager = None


# pull-based gauges over the CURRENT manager (0 before the first query
# builds one); producers pay nothing, the sampler reads at snapshot time
TM.REGISTRY.gauge(
    "tpuq_hbm_reserved_bytes", "bytes currently reserved in HBM",
    fn=lambda: _manager._reserved if _manager is not None else 0)
TM.REGISTRY.gauge(
    "tpuq_hbm_watermark_bytes", "peak reserved bytes (this manager)",
    fn=lambda: (_manager.metrics["peakReserved"]
                if _manager is not None else 0))
TM.REGISTRY.gauge(
    "tpuq_hbm_budget_bytes", "HBM budget the arbiter hands out",
    fn=lambda: _manager.budget if _manager is not None else 0)
TM.REGISTRY.gauge(
    "tpuq_host_spill_used_bytes", "host spill tier bytes in use",
    fn=lambda: _manager._host_used if _manager is not None else 0)
TM.REGISTRY.gauge(
    "tpuq_spillable_batches", "live registered spillable batches",
    fn=lambda: len(_manager._spillables) if _manager is not None else 0)


def _build(conf) -> DeviceMemoryManager:
    if conf is None:
        return DeviceMemoryManager()
    from spark_rapids_tpu import conf as C
    return DeviceMemoryManager(
        budget=conf.get(C.POOL_SIZE) or None,
        alloc_fraction=conf.get(C.MEMORY_FRACTION),
        host_limit=conf.get(C.HOST_SPILL_STORAGE),
        spill_path=conf.get(C.SPILL_PATH),
        inject_oom_at=conf.get(C.FAULT_INJECT),
        retry_max_attempts=conf.get(C.RETRY_MAX),
        debug=str(conf.get(C.MEMORY_DEBUG)).upper() == "STDOUT",
        conf=conf,
    )


# ---------------------------------------------------------------------------
# the retry framework [REF: RmmRapidsRetryIterator.scala :: withRetry]
# ---------------------------------------------------------------------------

def split_batch_in_half(batch: DeviceBatch) -> List[DeviceBatch]:
    """Halve a batch by row range (the splitSpillableInHalfByRows
    analog).  Static slicing — each half keeps a pow-2 capacity."""
    from spark_rapids_tpu.parallel.shuffle import slice_batch
    cap = batch.capacity
    if cap <= 1:
        raise SplitAndRetryOOM("cannot split a 1-row batch")
    half = cap // 2
    return [slice_batch(batch, 0, half), slice_batch(batch, half, half)]


def with_retry(
    inputs: Iterable[DeviceBatch],
    closure: Callable[[DeviceBatch], object],
    max_attempts: Optional[int] = None,
    manager: Optional[DeviceMemoryManager] = None,
    allow_split: bool = True,
):
    """Run ``closure`` over each input batch with OOM rollback.

    On ``RetryOOM``: spill registered spillables and re-run the same
    batch.  On ``SplitAndRetryOOM`` (or repeated RetryOOM): split the
    batch in half by rows and process the halves independently — the
    caller's closure must be merge-friendly (partial aggregates, sorted
    runs, ...).  Yields one result per processed (sub-)batch.

    Attempts default to the unified ``RetryPolicy``
    (``spark.rapids.tpu.retry.maxAttempts``), and every OOM retry is
    accounted as an ``alloc``-domain retry in
    ``tpuq_retry_total{domain="alloc"}`` — OOM rollback and device-call
    retries share the one policy.

    ``inputs`` is consumed LAZILY — one upstream batch is live at a
    time, so spilling actually frees HBM instead of fighting a pinned
    input list.
    """
    mgr = manager or get_manager()
    if max_attempts is None:
        max_attempts = R.get_policy().max_attempts
    it = iter(inputs)
    work: List[Tuple[DeviceBatch, int]] = []  # pending (sub-)batches
    while True:
        cancel.check()
        if work:
            batch, attempts = work.pop(0)
        else:
            batch = next(it, None)
            if batch is None:
                return
            attempts = 0
        try:
            yield closure(batch)
        except SplitAndRetryOOM:
            if not allow_split:
                raise
            mgr.metrics["splitRetries"] += 1
            _TM_SPLIT_RETRY.inc()
            R.note_retry("alloc")
            halves = split_batch_in_half(batch)
            work = [(h, attempts + 1) for h in halves] + work
        except RetryOOM:
            if attempts + 1 >= max_attempts:
                R.note_exhausted()
                raise
            R.note_retry("alloc")
            # free device pressure INCREMENTALLY: spill victims until
            # roughly this batch's working set is free, not the whole
            # pool (draining everything evicts the scan cache on the
            # first transient OOM and forces full re-materialization)
            freed, target = 0, max(batch.nbytes(), 1)
            for s in list(mgr._spillables.values()):
                if s.tier == "device":
                    freed += s.spill_to_host()
                    if freed >= target:
                        break
            if attempts >= 1 and allow_split and batch.capacity > 1:
                mgr.metrics["splitRetries"] += 1
                _TM_SPLIT_RETRY.inc()
                halves = split_batch_in_half(batch)
                work = [(h, attempts + 1) for h in halves] + work
            else:
                work.insert(0, (batch, attempts + 1))
