"""The shape plane — canonical batch-shape bucketing.

THE compile-storm killer (SURVEY §7, ROADMAP item 4): every kernel in
this engine compiles per (op, schema, row-bucket), so the number of
DISTINCT buckets flowing through the exec pump bounds the number of XLA
compiles a sweep can trigger.  Most producers already emit pow-2
capacities, but join group slicing, sub-partitioning, and concat
trimming can emit stragglers — each a fresh bucket, each a fresh
compile of every downstream kernel.  This module pins every pumped
``DeviceBatch`` to a small canonical ladder of row buckets at the
operator boundary (exec/base.py wires it under the stats/trace pumps),
collapsing ``runtime/kernel_cache.py`` key shapes onto the ladder.

Padding is dead-row padding: appended rows carry ``sel=False`` (and
zeroed data/validity/lengths planes), which every kernel already
ignores — the same liveness contract filtering rides.  A compacted
batch stays compacted: pad rows extend the dead tail, so the
``compacted`` promise (live rows at the front) is preserved and
downstream consumers still skip the compaction kernel.

Policies (``spark.rapids.tpu.kernel.bucketing``):

* ``pow2``   — round capacity up to the next power of two, floored at
  ``spark.rapids.tpu.minBucketRows`` (the engine's native bucketing;
  makes stragglers conform).
* ``ladder`` — round up to the smallest rung of the explicit
  ``spark.rapids.tpu.kernel.bucketLadder`` list; capacities above the
  top rung (and rungs that would exceed
  ``spark.rapids.tpu.kernel.maxPadFraction`` of padding) fall back to
  pow2.
* ``off``    — pass batches through untouched.

The plane is observable end-to-end: bucket hits/misses and pad-waste
counters in the telemetry registry, per-op ``padded_rows`` in the stats
plane, and a cold-vs-warm compile record in bench.py.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Tuple

from spark_rapids_tpu.runtime import telemetry as TM

_TM_HITS = TM.REGISTRY.counter(
    "tpuq_shape_bucket_hits_total",
    "pumped device batches whose capacity already sat on the bucket "
    "ladder (no padding)")
_TM_MISSES = TM.REGISTRY.counter(
    "tpuq_shape_bucket_misses_total",
    "pumped device batches padded up to a canonical bucket")
_TM_PAD_ROWS = TM.REGISTRY.counter(
    "tpuq_shape_pad_rows_total",
    "dead rows appended by shape-plane bucketing")
_TM_PAD_BYTES = TM.REGISTRY.counter(
    "tpuq_shape_pad_bytes_total",
    "physical bytes of shape-plane padding (pad-waste)")


@dataclasses.dataclass(frozen=True)
class ShapePolicy:
    """One immutable bucketing policy (the conf snapshot, parsed)."""

    mode: str = "off"                  # off | pow2 | ladder
    ladder: Tuple[int, ...] = ()       # strictly increasing rungs
    max_pad_fraction: float = 0.75     # ladder-rung pad budget
    min_bucket: int = 1 << 10

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    def bucket_for(self, capacity: int) -> int:
        """Canonical bucket (>= capacity) for a batch capacity.

        Ladder rungs are only taken within the pad budget; everything
        else (including capacities above the top rung) rounds pow2 —
        pow2 padding is at most half the bucket, so it always lands
        within the default budget and never needs its own check."""
        from spark_rapids_tpu.columnar.column import round_up_pow2
        capacity = max(int(capacity), 1)
        if self.mode == "ladder":
            for rung in self.ladder:
                if rung >= capacity:
                    if (rung - capacity) / rung <= self.max_pad_fraction:
                        return rung
                    break  # smallest fitting rung over budget: pow2
        return round_up_pow2(capacity, self.min_bucket)


# The active policy — module global, same pattern as lockdep.configure /
# telemetry.configure_sampler: the session snapshots conf once and every
# pump boundary reads one attribute.
_POLICY = ShapePolicy()
_LOCK = threading.Lock()


def configure(conf) -> ShapePolicy:
    """Install the policy from a RapidsConf snapshot (session init)."""
    from spark_rapids_tpu import conf as C
    mode = str(conf.get(C.KERNEL_BUCKETING)).lower()
    raw = str(conf.get(C.KERNEL_BUCKET_LADDER)).strip()
    ladder = tuple(int(x.strip()) for x in raw.split(",")) if raw else ()
    pol = ShapePolicy(
        mode=mode,
        ladder=ladder,
        max_pad_fraction=float(conf.get(C.KERNEL_MAX_PAD_FRACTION)),
        min_bucket=int(conf.get(C.MIN_BUCKET_ROWS)))
    global _POLICY
    with _LOCK:
        _POLICY = pol
    return pol


def current_policy() -> ShapePolicy:
    return _POLICY


def bucket_batch(batch, policy: Optional[ShapePolicy] = None):
    """(bucketed batch, padded row count) for one pumped DeviceBatch.

    Everything here is static host-side metadata — capacity and nbytes
    come from array SHAPES, so bucketing never forces a device sync.
    Non-DeviceBatch values (host batches crossing a transition) pass
    through untouched.
    """
    pol = policy if policy is not None else _POLICY
    if not pol.enabled:
        return batch, 0
    sel = getattr(batch, "sel", None)
    if sel is None:  # not a DeviceBatch
        return batch, 0
    cap = batch.capacity
    bucket = pol.bucket_for(cap)
    if bucket <= cap:
        _TM_HITS.inc()
        return batch, 0
    _TM_MISSES.inc()
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import DeviceBatch, _pad_col
    pad = bucket - cap
    cols = tuple(_pad_col(c, bucket) for c in batch.columns)
    out = DeviceBatch(batch.schema, cols,
                      jnp.pad(batch.sel, (0, pad)),
                      # dead-tail padding keeps live rows at the front,
                      # so the compacted promise survives
                      compacted=batch.compacted)
    _TM_PAD_ROWS.inc(pad)
    _TM_PAD_BYTES.inc(max(out.nbytes() - batch.nbytes(), 0))
    return out, pad


def retarget_bucket(rows: int) -> int:
    """Canonical bucket for an adaptive row target (adaptive plane's
    dynamic batch retargeting): when bucketing is on, snap the target
    to the ladder so retargeted reads coalesce onto compile-cached
    batch shapes instead of minting fresh (op, schema, bucket) keys;
    with the plane off, pow-2 round-up keeps the target on the native
    capacities producers already emit."""
    from spark_rapids_tpu.columnar.column import round_up_pow2
    rows = max(int(rows), 1)
    pol = _POLICY
    if pol.enabled:
        return pol.bucket_for(rows)
    return round_up_pow2(rows)


def snapshot() -> Tuple[int, int, int, int]:
    """(hits, misses, pad_rows, pad_bytes) — bench cold/warm deltas."""
    return (_TM_HITS.value, _TM_MISSES.value,
            _TM_PAD_ROWS.value, _TM_PAD_BYTES.value)
