"""Lockdep-style runtime lock-order watchdog.

The static ``lock-order`` lint rule (utils/lint/lock_order.py) sees
only what resolves statically — nested ``with`` scopes and calls it
can trace through names.  Locks handed through locals, dynamic
dispatch, and cross-module object graphs (the semaphore CV registering
with a cancel token, a spill callback re-entering the memory manager)
are out of its reach.  This module covers that gap at runtime, the way
the kernel's lockdep does: observe every acquisition, maintain one
process-wide acquisition-order graph, and flag the FIRST edge that
closes a cycle — turning a deadlock that needs a precise interleaving
into a deterministic report from ANY interleaving that exercises both
orders.

Mechanism
---------
``enable()`` replaces the ``threading.Lock`` / ``RLock`` /
``Condition`` factories with site-filtered shims: a lock whose
creation site is inside ``spark_rapids_tpu/`` gets a tracked wrapper,
anything else (jax, stdlib pools) gets the real primitive untouched.
Lock identity is the creation site (``runtime.memory.L448``) — one
identity covers every instance born there, because acquisition order
is a property of the code path, not the object.  Each thread keeps its
held list; acquiring B while holding A inserts edge A→B into the
process-wide graph (guarded by a real, untracked lock, with a
thread-local reentrancy latch so the watchdog's own bookkeeping and
telemetry can't recurse into itself).  ``Condition.wait`` releases the
held entry for its duration and re-records edges on reacquire.

A cycle is recorded as a :class:`Violation` (and raised as
:class:`LockOrderViolation` when ``raise_on_cycle``); tier-1 runs the
whole suite in record mode via tests/conftest.py and fails the session
on any unexempted violation.  A deliberate edge carries the uniform
annotation ``# lint: exempt(lockdep): <why>`` at the acquisition site.

Conf (read by ``TpuSession.__init__`` → :func:`configure`):

* ``spark.rapids.tpu.lockdep.enabled`` — install the shims
* ``spark.rapids.tpu.lockdep.raiseOnCycle`` — raise at the closing
  acquisition instead of only recording

Telemetry: ``tpuq_lockdep_locks_tracked``, ``tpuq_lockdep_edges_observed``,
``tpuq_lockdep_violations_total``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

# real primitives, captured before any patching
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_THREADING_FILE = threading.__file__


class LockOrderViolation(Exception):
    """Acquisition closed a cycle in the lock-order graph."""


@dataclasses.dataclass(frozen=True)
class Violation:
    edge: Tuple[str, str]          # the edge that closed the cycle
    cycle: Tuple[str, ...]         # key path b -> ... -> a
    site: Tuple[str, int]          # (rel path, line) of the acquisition
    thread: str

    def __str__(self) -> str:
        a, b = self.edge
        rel, line = self.site
        loop = " -> ".join(self.cycle + (self.cycle[0],))
        return (f"{rel}:{line}: lock-order cycle closed by {a} -> {b} "
                f"in thread {self.thread}: {loop}")


class _State:
    def __init__(self):
        self.enabled = False
        self.raise_on_cycle = False
        self.meta = _REAL_LOCK()
        self.graph: Dict[str, Set[str]] = {}
        self.edge_sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.violations: List[Violation] = []
        self.sites: Set[str] = set()   # distinct tracked lock keys


_S = _State()
_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


# -- telemetry (leaf tier; registered at import like every producer) -----
from spark_rapids_tpu.runtime import telemetry as TM  # noqa: E402

_TM_LOCKS = TM.REGISTRY.gauge(
    "tpuq_lockdep_locks_tracked",
    "distinct lock creation sites under lockdep tracking",
    fn=lambda: float(len(_S.sites)))
_TM_EDGES = TM.REGISTRY.gauge(
    "tpuq_lockdep_edges_observed",
    "distinct held->acquired edges in the runtime lock-order graph",
    fn=lambda: float(len(_S.edge_sites)))
_TM_VIOLATIONS = TM.REGISTRY.counter(
    "tpuq_lockdep_violations_total",
    "lock-order cycles observed by the lockdep watchdog")


# -- creation-site attribution -------------------------------------------

def _creation_site() -> Optional[Tuple[str, int]]:
    """(relpath, line) of the first caller frame outside this module
    and threading.py, if it lies inside the package; else None."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != __file__ and fn != _THREADING_FILE:
            if fn.startswith(_PKG_DIR + os.sep):
                return os.path.relpath(fn, os.path.dirname(_PKG_DIR)), \
                    f.f_lineno
            return None
        f = f.f_back
    return None


def _site_key(rel: str, line: int) -> str:
    s = rel.replace("\\", "/")
    if s.startswith("spark_rapids_tpu/"):
        s = s[len("spark_rapids_tpu/"):]
    if s.endswith(".py"):
        s = s[:-3]
    return f"{s.replace('/', '.')}.L{line}"


def _acquire_site() -> Tuple[str, int]:
    """(relpath, line) of the repo frame performing the acquisition —
    only walked when a violation actually fires."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if (fn != __file__ and fn != _THREADING_FILE
                and fn.startswith(os.path.dirname(_PKG_DIR) + os.sep)):
            return os.path.relpath(fn, os.path.dirname(_PKG_DIR)), \
                f.f_lineno
        f = f.f_back
    return "<unknown>", 0


# -- graph bookkeeping ----------------------------------------------------

def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> dst in the current graph, or None."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _S.graph.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquire(obj) -> None:
    if getattr(_tls, "in_hook", False):
        return
    _tls.in_hook = True
    try:
        held = _held()
        first = all(e is not obj for e in held)
        raised: Optional[Violation] = None
        if first and held:
            for h in held:
                if h._key == obj._key:
                    continue
                v = _add_edge(h._key, obj._key)
                if v is not None:
                    raised = v
        held.append(obj)
        if raised is not None and _S.raise_on_cycle:
            raise LockOrderViolation(str(raised))
    finally:
        _tls.in_hook = False


def _note_release(obj) -> None:
    if getattr(_tls, "in_hook", False):
        return
    held = getattr(_tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i] is obj:
            del held[i]
            return
    # released by a thread that never recorded the acquire — ignore


def _add_edge(a: str, b: str) -> Optional[Violation]:
    """Insert a→b; returns a Violation when it closes a cycle."""
    with _S.meta:
        succ = _S.graph.setdefault(a, set())
        if b in succ:
            return None
        back = _find_path(b, a)
        succ.add(b)
        _S.graph.setdefault(b, set())
        site = _acquire_site()
        _S.edge_sites[(a, b)] = site
        if back is None:
            return None
        v = Violation(edge=(a, b), cycle=tuple(back), site=site,
                      thread=threading.current_thread().name)
        _S.violations.append(v)
    _TM_VIOLATIONS.inc()
    return v


# -- tracked wrappers -----------------------------------------------------

class _TrackedLock:
    """Transparent Lock/RLock shim recording acquisition order."""

    def __init__(self, real, key: str, kind: str):
        self._real = real
        self._key = key
        self._kind = kind

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._real.acquire(blocking, timeout)
        if ok:
            _note_acquire(self)
        return ok

    def release(self):
        _note_release(self)
        self._real.release()

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockdep {self._kind} {self._key} of {self._real!r}>"


class _TrackedCondition:
    """Condition shim; ``wait`` drops the held entry for its duration
    so edges observed after wakeup reflect the reacquisition."""

    def __init__(self, real, key: str):
        self._real = real
        self._key = key
        self._kind = "Condition"

    def acquire(self, *a, **k):
        ok = self._real.acquire(*a, **k)
        if ok:
            _note_acquire(self)
        return ok

    def release(self):
        _note_release(self)
        self._real.release()

    def __enter__(self):
        self._real.__enter__()
        _note_acquire(self)
        return self

    def __exit__(self, *exc):
        _note_release(self)
        return self._real.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None):
        _note_release(self)
        try:
            # the CALLER owns the token-polling loop around this wait
            # cancel-exempt: lockdep shim forwards the caller's bounded wait
            return self._real.wait(timeout)
        finally:
            _note_acquire(self)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _note_release(self)
        try:
            return self._real.wait_for(predicate, timeout)
        finally:
            _note_acquire(self)

    def notify(self, n: int = 1):
        self._real.notify(n)

    def notify_all(self):
        self._real.notify_all()

    def __repr__(self):
        return f"<lockdep Condition {self._key} of {self._real!r}>"


def _register(key: str) -> None:
    with _S.meta:
        _S.sites.add(key)


def _make_lock():
    real = _REAL_LOCK()
    if not _S.enabled:
        return real
    site = _creation_site()
    if site is None:
        return real
    key = _site_key(*site)
    _register(key)
    return _TrackedLock(real, key, "Lock")


def _make_rlock():
    real = _REAL_RLOCK()
    if not _S.enabled:
        return real
    site = _creation_site()
    if site is None:
        return real
    key = _site_key(*site)
    _register(key)
    return _TrackedLock(real, key, "RLock")


def _make_condition(lock=None):
    if not _S.enabled:
        return _REAL_CONDITION(
            lock._real if isinstance(lock, _TrackedLock) else lock)
    if isinstance(lock, _TrackedLock):
        # the condition shares the lock's mutex — and its identity, so
        # `with self._lock:` and `with self._cv:` edges agree
        _register(lock._key)
        return _TrackedCondition(_REAL_CONDITION(lock._real), lock._key)
    site = _creation_site()
    if site is None:
        return _REAL_CONDITION(
            lock._real if isinstance(lock, _TrackedLock) else lock)
    key = _site_key(*site)
    _register(key)
    return _TrackedCondition(
        _REAL_CONDITION(lock if lock is not None else _REAL_RLOCK()),
        key)


def tracked_lock(key: str, reentrant: bool = False):
    """Explicitly-keyed tracked lock — lets tests (outside the package
    tree, hence invisible to the site filter) participate in the graph."""
    real = _REAL_RLOCK() if reentrant else _REAL_LOCK()
    _register(key)
    return _TrackedLock(real, key, "RLock" if reentrant else "Lock")


def tracked_condition(key: str):
    """Explicitly-keyed tracked condition, for tests."""
    _register(key)
    return _TrackedCondition(_REAL_CONDITION(_REAL_RLOCK()), key)


# -- lifecycle ------------------------------------------------------------

def enable(raise_on_cycle: bool = False) -> None:
    """Install the factory shims.  Locks created BEFORE this call stay
    untracked (module-level locks of already-imported modules)."""
    _S.raise_on_cycle = raise_on_cycle
    if _S.enabled:
        return
    _S.enabled = True
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition


def disable() -> None:
    if not _S.enabled:
        return
    _S.enabled = False
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION


def is_enabled() -> bool:
    return _S.enabled


def reset() -> None:
    """Clear the graph, edge sites, and violation log (tracked locks
    keep working; their next acquisitions rebuild the graph)."""
    with _S.meta:
        _S.graph.clear()
        _S.edge_sites.clear()
        _S.violations.clear()
        _S.sites.clear()


def violations() -> List[Violation]:
    with _S.meta:
        return list(_S.violations)


def edges() -> Dict[Tuple[str, str], Tuple[str, int]]:
    """(a, b) -> (rel path, line) of the first observation."""
    with _S.meta:
        return dict(_S.edge_sites)


@contextlib.contextmanager
def scoped(raise_on_cycle: bool = True):
    """Isolated graph for deliberate-inversion tests: swaps in fresh
    graph/violation state (and enables if needed) for the duration, so
    a seeded cycle can't fail the session-wide record-mode check."""
    with _S.meta:
        saved = (_S.graph, _S.edge_sites, _S.violations, _S.sites,
                 _S.raise_on_cycle, _S.enabled)
        _S.graph, _S.edge_sites = {}, {}
        _S.violations, _S.sites = [], set()
    was_enabled = saved[5]
    enable(raise_on_cycle=raise_on_cycle)
    _S.raise_on_cycle = raise_on_cycle
    try:
        yield _S
    finally:
        if not was_enabled:
            disable()   # while _S.enabled is still True, so it unpatches
        with _S.meta:
            (_S.graph, _S.edge_sites, _S.violations, _S.sites,
             _S.raise_on_cycle, _S.enabled) = saved


def configure(conf) -> None:
    """Session-init hook: conf-gated enablement
    (``spark.rapids.tpu.lockdep.enabled`` /
    ``spark.rapids.tpu.lockdep.raiseOnCycle``)."""
    from spark_rapids_tpu import conf as C
    if conf.get(C.LOCKDEP_ENABLED):
        enable(raise_on_cycle=bool(conf.get(C.LOCKDEP_RAISE_ON_CYCLE)))
