"""Query-scoped span tracing + persistent query event log.

The NVTX analog [REF: sql-plugin/../GpuMetrics.scala :: NvtxRange /
NvtxWithMetrics; spark-rapids-jni profiler]: every exec's partition pump
and its internal stages (compile, H2D transfer, device compute, D2H
gather, shuffle/collective) open spans on a per-query ``Tracer``.  Spans
nest per thread (the executor pool's task threads each keep their own
stack), accumulate their children's time so self-time vs total-time per
operator is finally attributable — the fix for ``opTime``
double-counting across parent/child iterators — and export as
Chrome-trace JSON (loadable in ``chrome://tracing`` / Perfetto).

The event log is the reference's driver-log "plan conversion report"
made machine-readable: one JSONL entry per query
(``spark.rapids.sql.queryLog.path``) recording the plan tree, the
device/fallback report from plan/overrides.py, every metric at its
level, the span rollup, and cross-links to the xplane profile dump and
LORE tag when enabled.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


class Span:
    """One timed range on one thread.  ``child_time`` accumulates the
    durations of directly-nested spans (any operator), so
    ``self_time = dur - child_time`` is this span's exclusive time."""

    __slots__ = ("op", "stage", "tid", "t0", "t1", "child_time",
                 "parent_op", "args")

    def __init__(self, op: str, stage: str, tid: int, t0: float,
                 parent_op: Optional[str], args: Optional[dict]):
        self.op = op
        self.stage = stage
        self.tid = tid
        self.t0 = t0
        self.t1 = t0
        self.child_time = 0.0
        self.parent_op = parent_op
        self.args = args

    @property
    def dur(self) -> float:
        return self.t1 - self.t0

    @property
    def self_time(self) -> float:
        return max(self.dur - self.child_time, 0.0)


class _SpanCtx:
    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self):
        return self._span

    def __exit__(self, *exc):
        self._tracer.end(self._span)
        return False


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class Tracer:
    """Thread-safe span collector for ONE query execution.

    Every thread keeps its own span stack (``threading.local``), so
    pump iterators nest correctly across the executor thread pool: a
    child operator's ``next()`` runs inside its consumer's span on the
    SAME thread and its duration subtracts from the consumer's
    self-time.  Spans on a pool thread with no enclosing span start a
    fresh top-level track for that thread."""

    def __init__(self, query_id: int, max_events: int = 100_000):
        self.query_id = query_id
        self.max_events = max_events
        self.t_start = time.perf_counter()
        self.wall_s: Optional[float] = None
        self.dropped = 0
        self.events: List[Span] = []
        # duck-typed flight-recorder hook (runtime/attribution.py):
        # when set, every closed span also lands in the recorder's
        # bounded ring — one extra deque append, no new timers
        self.recorder = None
        self._lock = threading.Lock()
        self._tls = threading.local()

    # -- recording ----------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def begin(self, op: str, stage: str,
              args: Optional[dict] = None) -> Span:
        st = self._stack()
        parent_op = st[-1].op if st else None
        sp = Span(op, stage, threading.get_ident(), time.perf_counter(),
                  parent_op, args)
        st.append(sp)
        return sp

    def end(self, span: Span) -> None:
        span.t1 = time.perf_counter()
        st = self._stack()
        # pop back to (and including) this span — tolerate a leaked
        # child that never closed (generator dropped mid-pump)
        while st and st[-1] is not span:
            st.pop()
        if st:
            st.pop()
        if st:
            st[-1].child_time += span.dur
        with self._lock:
            if len(self.events) < self.max_events:
                self.events.append(span)
            else:
                self.dropped += 1
        rec = self.recorder
        if rec is not None:
            rec.record_span(span)

    def span(self, op: str, stage: str, args: Optional[dict] = None):
        """Context manager recording one span."""
        return _SpanCtx(self, self.begin(op, stage, args))

    def finish(self) -> None:
        self.wall_s = time.perf_counter() - self.t_start

    # -- export -------------------------------------------------------------
    def to_chrome_trace(self) -> Dict[str, Any]:
        """The ``chrome://tracing`` / Perfetto JSON object format:
        complete ('X') events with microsecond timestamps relative to
        query start, one track per pump thread."""
        tids: Dict[int, int] = {}
        events: List[dict] = []
        with self._lock:
            spans = list(self.events)
        for sp in spans:
            tid = tids.setdefault(sp.tid, len(tids) + 1)
            ev = {
                "name": f"{sp.op}:{sp.stage}",
                "cat": sp.stage,
                "ph": "X",
                "ts": round((sp.t0 - self.t_start) * 1e6, 3),
                "dur": round(sp.dur * 1e6, 3),
                "pid": 1,
                "tid": tid,
            }
            if sp.args:
                ev["args"] = sp.args
            events.append(ev)
        for ident, tid in tids.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": f"pump-{tid}"
                         if tid > 1 else "query-main"},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "query_id": self.query_id,
                "dropped_spans": self.dropped,
            },
        }

    def rollup(self) -> Dict[str, Dict[str, Any]]:
        """Per-operator total vs self time derived from the span tree.

        ``total_s`` counts only spans NOT nested inside a span of the
        same operator (a pump span's internal opTime span must not
        double-count); ``self_s`` sums every span's exclusive time, so
        across all operators self times partition the traced wall time
        exactly — the attribution ``opTime`` alone cannot give."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            spans = list(self.events)
        for sp in spans:
            r = out.setdefault(sp.op, {
                "total_s": 0.0, "self_s": 0.0, "spans": 0, "stages": {}})
            r["spans"] += 1
            if sp.parent_op != sp.op:
                r["total_s"] += sp.dur
            r["self_s"] += sp.self_time
            st = r["stages"]
            st[sp.stage] = st.get(sp.stage, 0.0) + sp.self_time
        for r in out.values():
            r["total_s"] = round(r["total_s"], 6)
            r["self_s"] = round(r["self_s"], 6)
            r["stages"] = {k: round(v, 6)
                           for k, v in sorted(r["stages"].items())}
        return out


# ---------------------------------------------------------------------------
# The active tracer — one query at a time owns it
# ---------------------------------------------------------------------------

# Checked on every pump step, so it is a bare module global (one
# attribute load when tracing is off).  A second query starting while
# one is active (a sub-query planned during execution) rides the owner's
# spans instead of replacing the tracer.
_ACTIVE: Optional[Tracer] = None
_ACTIVE_LOCK = threading.Lock()
_QUERY_IDS = itertools.count(1)


def next_query_id() -> int:
    return next(_QUERY_IDS)


def current() -> Optional[Tracer]:
    return _ACTIVE


def start_query(query_id: int, max_events: int = 100_000
                ) -> Optional[Tracer]:
    """Install a fresh tracer; returns None when another query already
    owns tracing (the caller is a nested execution)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            return None
        _ACTIVE = Tracer(query_id, max_events=max_events)
        return _ACTIVE


def end_query(tracer: Optional[Tracer]) -> None:
    global _ACTIVE
    if tracer is None:
        return
    tracer.finish()
    with _ACTIVE_LOCK:
        if _ACTIVE is tracer:
            _ACTIVE = None


def span(op: str, stage: str, args: Optional[dict] = None):
    """Span on the active tracer, or a no-op when tracing is off —
    THE hook free-standing stages (kernel compile, spill, shuffle
    serialize) use without carrying a tracer reference."""
    tr = _ACTIVE
    if tr is None:
        return _NULL
    return tr.span(op, stage, args)


# ---------------------------------------------------------------------------
# Query event log
# ---------------------------------------------------------------------------

def plan_metrics(plan) -> List[dict]:
    """Every node's metrics WITH their verbosity levels — the event log
    records all levels; readers filter."""
    out: List[dict] = []

    def walk(node):
        out.append({
            "op": type(node).__name__,
            "metrics": {
                name: {"value": (round(m.value, 6)
                                 if isinstance(m.value, float)
                                 else m.value),
                       "level": m.level}
                for name, m in getattr(node, "metrics", {}).items()},
        })
        for c in node.children:
            walk(c)

    walk(plan)
    return out


_LOG_LOCK = threading.Lock()


def append_query_log(path: str, entry: Dict[str, Any]) -> None:
    """Append one JSONL record; directory auto-created.  Failures are
    swallowed to stderr — observability must never fail the query."""
    import sys
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        line = json.dumps(entry, default=str)
        with _LOG_LOCK:
            with open(path, "a") as f:
                f.write(line + "\n")
    except OSError as e:
        print(f"[tpuq] query log write failed: {e}", file=sys.stderr,
              flush=True)


def write_chrome_trace(dir_path: str, tracer: Tracer) -> Optional[str]:
    """``<dir>/query-<id>.trace.json``; returns the path (None on
    failure)."""
    import sys
    try:
        os.makedirs(dir_path, exist_ok=True)
        out = os.path.join(dir_path,
                           f"query-{tracer.query_id:06d}.trace.json")
        with open(out, "w") as f:
            json.dump(tracer.to_chrome_trace(), f)
        return out
    except OSError as e:
        print(f"[tpuq] chrome trace write failed: {e}", file=sys.stderr,
              flush=True)
        return None
