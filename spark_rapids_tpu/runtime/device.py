"""Device runtime bootstrap — the ``GpuDeviceManager`` analog.

[REF: sql-plugin/../GpuDeviceManager.scala :: initializeGpuAndMemory]
Responsible for one-time engine initialization: exact-numerics mode (x64),
device discovery, and (see ``runtime/memory.py``) the HBM budget arbiter.
"""

from __future__ import annotations

import threading

_init_lock = threading.Lock()
_initialized = False


def ensure_initialized() -> None:
    """One-time engine init.  Called by every engine entry point (session
    creation, host<->device transfer), NOT at import, so importing the
    package does not change process-global JAX semantics for host programs
    that never run a query.

    SQL engines need exact 64-bit integer/floating semantics (Spark
    LongType/DoubleType, Decimal backed by int64), so x64 is enabled for the
    process once the engine is actually used.  TPU emulates int64;
    correctness over raw speed — hot kernels opt into 32-bit where safe.
    """
    global _initialized
    if _initialized:
        return
    with _init_lock:
        if _initialized:
            return
        import jax

        jax.config.update("jax_enable_x64", True)
        _initialized = True


def device_count() -> int:
    ensure_initialized()
    import jax

    return jax.device_count()


def local_device() -> "object":
    ensure_initialized()
    import jax

    return jax.local_devices()[0]
