"""Device runtime bootstrap — the ``GpuDeviceManager`` analog.

[REF: sql-plugin/../GpuDeviceManager.scala :: initializeGpuAndMemory]
Responsible for one-time engine initialization: exact-numerics mode (x64),
device discovery, and (see ``runtime/memory.py``) the HBM budget arbiter.
"""

from __future__ import annotations

import threading

_init_lock = threading.Lock()
_initialized = False


def ensure_initialized() -> None:
    """One-time engine init.  Called by every engine entry point (session
    creation, host<->device transfer), NOT at import, so importing the
    package does not change process-global JAX semantics for host programs
    that never run a query.

    SQL engines need exact 64-bit integer/floating semantics (Spark
    LongType/DoubleType, Decimal backed by int64), so x64 is enabled for the
    process once the engine is actually used.  TPU emulates int64;
    correctness over raw speed — hot kernels opt into 32-bit where safe.
    """
    global _initialized
    if _initialized:
        return
    with _init_lock:
        if _initialized:
            return
        import os

        import jax

        jax.config.update("jax_enable_x64", True)
        # Persistent XLA executable cache: operator kernels (sort-heavy,
        # expensive to compile on TPU) compile once per machine, not per
        # process.  Measured on the real chip: a 3-key sort kernel costs
        # ~2 min to compile and ~0.7 ms to run — the cache is what makes
        # the (op, schema, bucket) executable-reuse design (SURVEY §7)
        # hold across sessions.
        cache_dir = os.environ.get(
            "SPARK_RAPIDS_TPU_XLA_CACHE",
            os.path.expanduser("~/.cache/spark_rapids_tpu/xla_cache"))
        # The persistent cache exists for TPU compile times (minutes per
        # sort kernel).  On the CPU platform it is DISABLED: XLA:CPU AOT
        # executables carry target pseudo-features (+prefer-no-gather …)
        # the loader's host check rejects, and reading such an entry
        # SEGFAULTS the process (observed under the test suite's forced
        # CPU platform — same machine, fresh cache).
        # resolved backend, not the config string — jax_platforms is
        # None when jax auto-selects, which is exactly the no-TPU host
        # case that must NOT get a persistent cache
        on_cpu = jax.default_backend() == "cpu"
        if cache_dir and not on_cpu:
            cache_dir = os.path.join(cache_dir, _machine_fingerprint())
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        _initialized = True


def _machine_fingerprint() -> str:
    """Short hash of the host's CPU feature flags."""
    import hashlib
    import platform
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha1(
                        line.encode()).hexdigest()[:12]
    except OSError:
        pass
    return platform.machine()


def device_count() -> int:
    ensure_initialized()
    import jax

    return jax.device_count()


def local_device() -> "object":
    ensure_initialized()
    import jax

    return jax.local_devices()[0]
