"""Unified resilience layer: failure domains, retry policy, breakers.

[REF: spark-rapids-jni :: src/main/cpp/faultinj/ — the LD_PRELOAD CUDA
 interceptor forcing errors at arbitrary driver entry points;
 sql-plugin :: RmmRapidsRetryIterator.scala — the uniform
 rollback-and-retry contract every device step gets; SURVEY §3.5/§5.3]

The engine's device/IO boundaries are twelve named **failure domains**:

======================  ====================================  ==========
domain                  chokepoint                            degradable
======================  ====================================  ==========
``execute``             kernel dispatch (kernel_cache)        yes: eager
``transfer``            device→host pull (columnar.column)    yes: sync
``alloc``               HBM reservation (runtime.memory)      via OOM retry
``spill_write``         host→disk spill (np.savez)            yes: stay host
``spill_read``          disk→host restore (np.load)           no (data gone)
``shuffle_ser``         tudo serialization (shuffle.manager)  no
``shuffle_exchange``    reduce-side shuffle read              no
``collective``          ICI all-to-all (exec.distributed)     yes: host shuffle
``compile``             jit wrapper build (kernel_cache)      yes: un-jitted
``rendezvous``          coordinator barrier (parallel.        no: epoch retry
                        rendezvous :: allgather)
``peer_loss``           simulated executor death              no: fails slice
``tenancy``             cluster directive apply (runtime.     yes: local-only
                        tenancy :: on_heartbeat)              enforcement
======================  ====================================  ==========

The distributed domains retry differently: ``rendezvous`` faults
re-enter the stage at epoch+1 through ``run_stage_epochs`` (same
policy, same budget), and ``peer_loss`` is always terminal — every
survivor raises the same peer-tagged ``TerminalDeviceError`` within
~one heartbeat lease (see docs/resilience.md, "Distributed failure
domains").  ``tenancy`` degrades softest of all: an injected (or
real) fault in the directive path drops that heartbeat's directives —
suspends are coordinator-renewed leases, so the protocol re-converges
on the next beat, and a sustained outage just means local-only
enforcement (never an error surfaced to a query).

Three cooperating pieces, all conf-driven:

* ``INJECTOR`` — a registry of independently armable fault injectors,
  one per domain (``spark.rapids.tpu.test.inject.<domain>.{at,
  transientCount}``), keeping the original self-disarm/transient-budget
  firing model.  The ``armed`` flag is a plain attribute written only
  under the lock, so the disarmed fast path is one atomic attribute
  read and an ARMED injector is never skipped by a racing pump thread
  (the old per-field fast-path reads could miss a concurrent arm).
* ``RetryPolicy`` — ``retry.maxAttempts`` attempts with exponential
  backoff (``retry.backoffBaseMs``..``retry.backoffMaxMs``) and
  deterministic seeded jitter (``retry.jitterSeed``), spending from a
  per-query retry budget (``retry.budgetPerQuery``).
* per-op **circuit breakers** — on retry exhaustion in a degradable
  domain the op's breaker trips and the step re-runs on the host path;
  later calls of the same op inside the query skip straight to the host
  path.  Non-degradable domains raise a domain-tagged
  ``TerminalDeviceError`` instead.  Every degradation is recorded in
  the query event log, emits a health WARN, and counts in
  ``tpuq_host_degraded_ops_total``.
"""

from __future__ import annotations

import random
import threading
import time
import zipfile
from typing import Callable, Dict, List, Optional, Tuple

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.runtime import cancel
from spark_rapids_tpu.runtime import telemetry as TM

DOMAINS: Tuple[str, ...] = C.FAILURE_DOMAINS

# domains whose exhaustion can re-run on the host path (graceful
# degradation); the rest raise a domain-tagged terminal error
DEGRADABLE = frozenset(
    {"execute", "transfer", "spill_write", "collective", "compile"})

# IO-backed domains also retry real filesystem faults, not only
# injected ones (a flaky NFS spill dir, a vanished shuffle file)
_IO_RETRYABLE = (OSError, EOFError, zipfile.BadZipFile)
_IO_DOMAINS = frozenset(
    {"spill_write", "spill_read", "shuffle_ser", "shuffle_exchange"})

_TM_RETRY = TM.REGISTRY.labeled_counter(
    "tpuq_retry_total",
    "retries performed by the unified retry policy, per failure domain")
_TM_INJECTED = TM.REGISTRY.labeled_counter(
    "tpuq_faults_injected_total",
    "fault-injector fires, per failure domain")
_TM_EXHAUSTED = TM.REGISTRY.counter(
    "tpuq_retry_exhausted_total",
    "device/IO steps whose retries were exhausted (incl. terminal "
    "faults, which exhaust immediately)")
_TM_BREAKER = TM.REGISTRY.counter(
    "tpuq_breaker_trips_total",
    "per-op circuit breakers tripped by retry exhaustion")
_TM_DEGRADED = TM.REGISTRY.counter(
    "tpuq_host_degraded_ops_total",
    "op executions served by the host degradation path")


class InjectedDeviceError(RuntimeError):
    """A fault-injected device/IO error (any failure domain)."""

    def __init__(self, where: str, nth: int, transient: bool):
        super().__init__(
            f"injected {where} error at call #{nth} "
            f"({'transient' if transient else 'terminal'})")
        self.where = where
        self.transient = transient

    @property
    def domain(self) -> str:
        return self.where


class TerminalDeviceError(RuntimeError):
    """A failure domain gave up: retries exhausted (or the fault was
    terminal) and no host degradation applied.  Domain-tagged so chaos
    harnesses and operators see WHICH boundary failed — a bare
    ``InjectedDeviceError`` never escapes the engine."""

    def __init__(self, domain: str, cause: BaseException,
                 attempts: int = 1):
        super().__init__(
            f"{domain} failed after {attempts} attempt(s): "
            f"{type(cause).__name__}: {cause}")
        self.domain = domain
        self.cause = cause
        self.attempts = attempts

    @property
    def transient(self) -> bool:
        """True when the underlying fault was transient (retries were
        exhausted on a fault that kept firing)."""
        return bool(getattr(self.cause, "transient", False))

    @property
    def peer(self):
        """The dead executor's pid for ``peer_loss`` failures (from the
        underlying ``RendezvousAborted``); None elsewhere."""
        return getattr(self.cause, "peer", None)


class _DomainState:
    __slots__ = ("at", "budget", "count", "fired")

    def __init__(self, at: int = -1, budget: int = 0):
        self.at = int(at)
        self.budget = int(budget)
        self.count = 0
        self.fired = 0


class FaultInjector:
    """Registry of per-domain injectors (the generalized ``_Injector``).

    Firing model per domain: once its call count reaches the configured
    N it starts firing.  With ``transient budget == 0`` the fire is
    terminal and the domain disarms.  With a budget K > 0, K consecutive
    calls fire transient and then the domain disarms — K = 1 proves
    single-retry recovery; K ≥ the engine's retry attempts models a
    persistent fault.  Disarming on exhaustion means an armed injection
    never leaks into later queries.

    ``armed`` is a plain bool attribute recomputed under the lock on
    every state change; ``on()``'s fast path is a single atomic read, so
    a concurrent pump thread can never observe stale per-domain fields
    and skip an armed injection.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.armed = False
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._config: Optional[tuple] = None
            self._domains: Dict[str, _DomainState] = {
                d: _DomainState() for d in DOMAINS}
            self.armed = False

    def configure(self, domains: Dict[str, Tuple[int, int]]) -> None:
        """Arm from {domain: (at, transient_budget)}; unlisted domains
        disarm.  Call counts restart at zero."""
        with self._lock:
            self._config = tuple(sorted(
                (d, int(at), int(b)) for d, (at, b) in domains.items()))
            self._domains = {d: _DomainState() for d in DOMAINS}
            for d, (at, budget) in domains.items():
                if d not in self._domains:
                    raise ValueError(f"unknown failure domain {d!r}; "
                                     f"expected one of {DOMAINS}")
                self._domains[d] = _DomainState(at, budget)
            self._recompute_armed()

    def configure_legacy(self, exec_at: int, transfer_at: int,
                         transient_count: int) -> None:
        """The original two-chokepoint signature (execute/transfer with
        a shared transient budget)."""
        self.configure({"execute": (exec_at, transient_count),
                        "transfer": (transfer_at, transient_count)})

    def _recompute_armed(self) -> None:
        # callers hold self._lock
        self.armed = any(s.at >= 0 for s in self._domains.values())

    def domain_armed(self, domain: str) -> bool:
        with self._lock:
            return self._domains[domain].at >= 0

    def on(self, domain: str) -> None:
        """The chokepoint: count this call and fire if configured."""
        if not self.armed:
            return
        with self._lock:
            s = self._domains[domain]
            if s.at < 0:
                return
            s.count += 1
            if s.count < s.at:
                return
            transient = s.fired < s.budget
            if transient:
                s.fired += 1
                if s.fired >= s.budget:
                    s.at = -1  # budget spent: later calls pass
            else:
                s.at = -1  # terminal
            self._recompute_armed()
            n = s.count
        _TM_INJECTED.inc(domain)
        raise InjectedDeviceError(domain, n, transient)

    # -- original chokepoint names (compat) -----------------------------
    def on_execute(self) -> None:
        self.on("execute")

    def on_transfer(self) -> None:
        self.on("transfer")


INJECTOR = FaultInjector()


def configure_from_conf(conf) -> None:
    """Arm the injector and refresh the retry policy from a session
    conf.  Injection reconfigures only when the requested config
    CHANGES — a conf with every injection key at its default never
    touches the injector, so concurrent clean sessions (planning,
    explain()) cannot disarm another session's armed injection.  Disarm
    happens via terminal self-disarm or ``INJECTOR.reset()``."""
    configure_policy(conf)
    legacy_ex = int(conf.get(C.INJECT_EXECUTE_AT))
    legacy_tr = int(conf.get(C.INJECT_TRANSFER_AT))
    legacy_tc = int(conf.get(C.INJECT_TRANSIENT_COUNT))
    requested: Dict[str, Tuple[int, int]] = {}
    for d in DOMAINS:
        at = int(conf.get(C.INJECT_DOMAIN_AT[d]))
        budget = int(conf.get(C.INJECT_DOMAIN_TRANSIENT[d]))
        # legacy execute/transfer keys map onto their domains unless the
        # domain key is set explicitly
        if at < 0 and d == "execute" and legacy_ex >= 0:
            at, budget = legacy_ex, legacy_tc
        if at < 0 and d == "transfer" and legacy_tr >= 0:
            at, budget = legacy_tr, legacy_tc
        if at >= 0:
            requested[d] = (at, budget)
    if not requested:
        return
    config_token = tuple(sorted(
        (d, at, b) for d, (at, b) in requested.items()))
    # reconfigure on a CHANGED config, or re-arm an identical config
    # whose fires are fully spent (per-query determinism) — but never
    # while any domain of the current config is still armed, which
    # would reset another in-flight query's injection pattern
    if INJECTOR._config != config_token or not INJECTOR.armed:
        INJECTOR.configure(requested)


# ---------------------------------------------------------------------------
# retry policy + per-query state (budget, breakers, degradations)
# ---------------------------------------------------------------------------

class _QueryState:
    """Per-query resilience scope shared by all pump threads: the retry
    budget, tripped breakers, and degradation records.  Reset on
    ``begin_query``; read out by ``finish_query`` into the event log."""

    def __init__(self):
        self.lock = threading.Lock()
        self.query_id: Optional[int] = None
        self.depth = 0  # nested executions share the outer scope
        self.retries_used = 0
        self.breakers: set = set()
        self.degraded_ops: List[dict] = []
        self.retries_by_domain: Dict[str, int] = {}
        self.exhausted = 0


_STATE = _QueryState()


class RetryPolicy:
    """Conf-driven retry contract every failure domain shares."""

    def __init__(self, max_attempts: int = 8,
                 backoff_base_ms: float = 5.0,
                 backoff_max_ms: float = 1000.0,
                 jitter_seed: int = 0,
                 budget_per_query: int = 64,
                 host_degrade: bool = True):
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base_ms = float(backoff_base_ms)
        self.backoff_max_ms = float(backoff_max_ms)
        self.jitter_seed = int(jitter_seed)
        self.budget_per_query = int(budget_per_query)
        self.host_degrade = bool(host_degrade)

    def _token(self) -> tuple:
        return (self.max_attempts, self.backoff_base_ms,
                self.backoff_max_ms, self.jitter_seed,
                self.budget_per_query, self.host_degrade)

    def backoff_s(self, domain: str, attempt: int) -> float:
        """Exponential backoff with deterministic seeded jitter: a pure
        function of (seed, domain, attempt) so chaos runs replay
        exactly."""
        if self.backoff_base_ms <= 0:
            return 0.0
        base = min(self.backoff_base_ms * (2 ** (attempt - 1)),
                   self.backoff_max_ms)
        rnd = random.Random(f"{self.jitter_seed}:{domain}:{attempt}")
        return base * (0.5 + 0.5 * rnd.random()) / 1000.0

    def _retryable(self, domain: str, exc: BaseException) -> bool:
        if isinstance(exc, InjectedDeviceError):
            return True
        if domain in _IO_DOMAINS and isinstance(exc, _IO_RETRYABLE):
            return True
        # a corrupt .npz payload surfaces from np.load as ValueError —
        # still a spill-tier IO fault, still domain-tagged on exhaustion
        if domain == "spill_read" and isinstance(exc, ValueError):
            return True
        # only the abort/timeout family of rendezvous failures retries
        # (epoch re-entry); protocol errors and dead peers never do
        if (domain == "rendezvous"
                and getattr(exc, "rendezvous_retryable", False)):
            return True
        return False

    def _budget_left(self) -> bool:
        if self.budget_per_query <= 0 or _STATE.depth == 0:
            return True  # budget is a per-query notion
        with _STATE.lock:
            return _STATE.retries_used < self.budget_per_query

    def run(self, domain: str, fn: Callable, *,
            op: Optional[str] = None,
            degrade: Optional[Callable] = None):
        """Run one device/IO step under the policy.

        ``fn`` performs the step (firing the domain's injection
        chokepoint itself, so retries re-arm against the injector).
        ``degrade``, when given and enabled, is the host path taken on
        retry exhaustion — its success is recorded as a degraded op.
        Without a degrade path, exhaustion raises a domain-tagged
        ``TerminalDeviceError``."""
        op_key = (domain, op or domain)
        if degrade is not None and breaker_open(op_key):
            _TM_DEGRADED.inc()
            return degrade()
        attempt = 0
        while True:
            attempt += 1
            cancel.check()
            try:
                return fn()
            except BaseException as e:
                if not self._retryable(domain, e):
                    raise
                transient = bool(getattr(e, "transient", True))
                if (transient and attempt < self.max_attempts
                        and self._budget_left()):
                    note_retry(domain)
                    delay = self.backoff_s(domain, attempt)
                    if delay > 0:
                        cancel.sleep(delay)
                    continue
                note_exhausted()
                if degrade is not None and self.host_degrade:
                    _trip_breaker(op_key, domain, op, e)
                    _TM_DEGRADED.inc()
                    return degrade()
                raise TerminalDeviceError(domain, e, attempt) from e


_policy = RetryPolicy()
_policy_lock = threading.Lock()


def get_policy() -> RetryPolicy:
    return _policy


def configure_policy(conf) -> RetryPolicy:
    """Refresh the process policy from a session conf (same
    last-writer-wins model as the memory manager)."""
    global _policy
    cfg = RetryPolicy(
        max_attempts=conf.get(C.RETRY_MAX),
        backoff_base_ms=conf.get(C.RETRY_BACKOFF_BASE_MS),
        backoff_max_ms=conf.get(C.RETRY_BACKOFF_MAX_MS),
        jitter_seed=conf.get(C.RETRY_JITTER_SEED),
        budget_per_query=conf.get(C.RETRY_BUDGET_PER_QUERY),
        host_degrade=conf.get(C.RETRY_HOST_DEGRADE),
    )
    with _policy_lock:
        if cfg._token() != _policy._token():
            _policy = cfg
    return _policy


def active() -> bool:
    """Cheap hot-path check: anything armed or any breaker open?  The
    disarmed/closed case is two attribute reads — kernel dispatch and
    D2H wrap themselves in the policy only when this is True."""
    return INJECTOR.armed or bool(_STATE.breakers)


def note_retry(domain: str) -> None:
    """Count one retry against the labeled counter and the per-query
    budget.  Also the hook ``with_retry`` (alloc/OOM rollback) calls so
    every retry in the engine lands in one place."""
    _TM_RETRY.inc(domain)
    # flight recorder: a retry burst right before a timeout is exactly
    # the evidence the black box exists to preserve
    from spark_rapids_tpu.runtime import attribution
    attribution.record_event("retry", {"domain": domain})
    with _STATE.lock:
        _STATE.retries_used += 1
        _STATE.retries_by_domain[domain] = (
            _STATE.retries_by_domain.get(domain, 0) + 1)


def note_exhausted() -> None:
    _TM_EXHAUSTED.inc()
    with _STATE.lock:
        _STATE.exhausted += 1


def breaker_open(op_key: tuple) -> bool:
    with _STATE.lock:
        return op_key in _STATE.breakers


def _trip_breaker(op_key: tuple, domain: str, op: Optional[str],
                  cause: BaseException) -> None:
    rec = {"domain": domain, "op": op or domain,
           "cause": f"{type(cause).__name__}: {cause}"}
    with _STATE.lock:
        fresh = op_key not in _STATE.breakers
        if fresh:
            _STATE.breakers.add(op_key)
        _STATE.degraded_ops.append(rec)
        qid = _STATE.query_id
    if fresh:
        _TM_BREAKER.inc()
    TM.REGISTRY.record_health({
        "severity": "WARN", "check": "host_degraded", "value": 1,
        "threshold": 0, "query_id": qid,
        "detail": (f"{domain} op {rec['op']!r} degraded to the host "
                   f"path after retry exhaustion ({rec['cause']})")})


def run_guarded(domain: str, fn: Callable, *, op: Optional[str] = None,
                degrade: Optional[Callable] = None):
    """Module-level convenience: ``get_policy().run(...)``."""
    return get_policy().run(domain, fn, op=op, degrade=degrade)


def begin_query(query_id: int) -> Optional[_QueryState]:
    """Open (or join) the query's resilience scope.  Nested executions
    (a sub-query pumped during an outer query) share the outer scope;
    only the outermost begin resets budget/breakers/records."""
    with _STATE.lock:
        _STATE.depth += 1
        if _STATE.depth > 1:
            return None  # joined an existing scope
        _STATE.query_id = query_id
        _STATE.retries_used = 0
        _STATE.breakers = set()
        _STATE.degraded_ops = []
        _STATE.retries_by_domain = {}
        _STATE.exhausted = 0
    return _STATE


def finish_query(scope: Optional[_QueryState]) -> Optional[dict]:
    """Close the scope opened by ``begin_query``; the outermost close
    returns the query's resilience record for the event log (None when
    nothing happened)."""
    with _STATE.lock:
        _STATE.depth = max(0, _STATE.depth - 1)
        if scope is None or _STATE.depth > 0:
            return None
        out = {
            "retries": dict(_STATE.retries_by_domain),
            "retries_total": _STATE.retries_used,
            "retry_exhausted": _STATE.exhausted,
            "breaker_trips": len(_STATE.breakers),
            "degraded_ops": list(_STATE.degraded_ops),
        }
        _STATE.query_id = None
    if not (out["retries"] or out["retry_exhausted"]
            or out["degraded_ops"]):
        return None
    return out


def counters_snapshot() -> dict:
    """Process-cumulative resilience counters (bench reporting)."""
    return {
        "retries": _TM_RETRY.child_values(),
        "faults_injected": _TM_INJECTED.child_values(),
        "retry_exhausted": _TM_EXHAUSTED.value,
        "breaker_trips": _TM_BREAKER.value,
        "host_degraded_ops": _TM_DEGRADED.value,
    }


def retry_device_call(fn, *args, max_attempts: Optional[int] = None,
                      **kw):
    """Back-compat wrapper for the original faultinj API: run a device
    call retrying transient injected faults, attempts governed by the
    conf-driven policy (``spark.rapids.tpu.retry.maxAttempts``) instead
    of the old hardcoded 2."""
    attempts = max_attempts or get_policy().max_attempts
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kw)
        except InjectedDeviceError as e:
            if not e.transient or attempt >= attempts:
                raise
            note_retry(e.domain)
