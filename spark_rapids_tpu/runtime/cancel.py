"""Query lifecycle layer: cooperative cancellation + deadlines.

[REF: Spark's task-kill/interrupt lifecycle (TaskContext.isInterrupted
 polled by long-running tasks) + spill/SpillFramework.scala's
 close-on-task-completion guarantees; GpuSemaphore.scala releases its
 permit on task completion callbacks, cancelled or not.]

The engine can retry (runtime/resilience.py) and detect dead peers
(parallel/rendezvous.py) but a serving stack must also be able to
**stop**: any query can be cancelled (``session.cancel(query_id)``) or
deadlined (``df.collect(timeout_ms=...)`` /
``spark.rapids.tpu.query.timeoutMs``) and the engine returns to a clean
steady state — semaphore permits released, HBM reservations unwound,
spill files unlinked, rendezvous peers fast-aborted.

Design: one ``CancelToken`` per query, opened by the query boundary
(``DataFrame.toArrow``) and **polled at every blocking boundary**:

* exec pump loops (``exec/base.py`` wraps every ``execute``),
* ``DeviceSemaphore.acquire`` (deadline-aware wait a cancel wakes),
* ``RetryPolicy`` backoff sleeps and the OOM retry loop,
* spill write/read (via the guarded retry loop) and shuffle exchange
  materialization,
* rendezvous stage waits (a cancel fast-aborts the epoch so peers are
  not wedged waiting for a cancelled participant).

Cancellation is COOPERATIVE: a blocking wait either registers its
condition variable with the token (woken instantly) or bounds the wait
by ``spark.rapids.tpu.query.cancelPollMs`` — either way a cancel
surfaces as ``QueryCancelled`` within ~2x the poll interval.  The
query boundary then guarantees reclamation (see
``DataFrame._reclaim_cancelled``): ``DeviceMemoryManager.report_leaks()``
returns 0 after every cancelled query.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, List, Optional

from spark_rapids_tpu.runtime import telemetry as TM

_TM_CANCELLED = TM.REGISTRY.labeled_counter(
    "tpuq_query_cancelled_total",
    "queries cancelled, by reason (user | deadline)", label="reason")
_TM_LATENCY = TM.REGISTRY.histogram(
    "tpuq_cancel_latency_seconds",
    "cancel-request (or deadline-expiry) to QueryCancelled-raised "
    "latency")
_TM_PREEMPT_REQ = TM.REGISTRY.counter(
    "tpuq_preempt_requests_total",
    "suspend requests issued against running queries")
_TM_PREEMPT_SUSPENDED = TM.REGISTRY.counter(
    "tpuq_preempt_suspended_total",
    "queries that reached the SUSPENDED state (permits released, "
    "residency spilled)")
_TM_PREEMPT_RESUMED = TM.REGISTRY.counter(
    "tpuq_preempt_resumed_total",
    "suspended queries resumed by the scheduler")
_TM_SUSPEND_LATENCY = TM.REGISTRY.histogram(
    "tpuq_preempt_suspend_latency_seconds",
    "suspend-request to SUSPENDED (first thread parked, permits "
    "released) latency")
_TM_PREEMPT_FORCE_RESUMED = TM.REGISTRY.counter(
    "tpuq_preempt_force_resumed_total",
    "suspends whose lease (ttl) expired unrenewed — requester died or "
    "coordinator lost — and the token force-resumed itself (the wedge "
    "guard)")

DEFAULT_POLL_S = 0.05

# -- preemption state machine (RUN -> SUSPEND_REQUESTED -> SUSPENDED ->
#    RESUMED).  The cancel plane's poll points are exactly the yield
#    points, so the same token carries both orders: ``check()`` asks
#    "must I die?", ``preempt_point()`` asks "must I yield?".
PREEMPT_RUN = "RUN"
PREEMPT_SUSPEND_REQUESTED = "SUSPEND_REQUESTED"
PREEMPT_SUSPENDED = "SUSPENDED"
PREEMPT_RESUMED = "RESUMED"

# (suspend_fn(token) -> state | None, resume_fn(token, state)) pairs a
# resource layer registers so a suspending THREAD can hand back what it
# holds (the device semaphore registers its per-thread permit stack)
# and take it back on resume.  suspend_fn returning None means the
# thread held nothing from that layer.
_SUSPEND_PROVIDERS: List[tuple] = []


def register_suspend_provider(suspend_fn: Callable,
                              resume_fn: Callable) -> None:
    """Register a (suspend, resume) pair run around every suspended
    park: ``suspend_fn(token)`` releases the calling thread's holdings
    and returns an opaque state (or None), ``resume_fn(token, state)``
    reacquires them after the scheduler resumes the query."""
    _SUSPEND_PROVIDERS.append((suspend_fn, resume_fn))


class QueryCancelled(RuntimeError):
    """The query's CancelToken fired.  Non-retryable by design: the
    retry policy, the OOM retry framework, and the rendezvous epoch
    loop all propagate it unchanged (it is not a fault — it is an
    order)."""

    def __init__(self, reason: str, query_id: Optional[int] = None,
                 detail: str = ""):
        msg = f"query {query_id} cancelled ({reason})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.reason = reason          # "user" | "deadline"
        self.query_id = query_id


class CancelToken:
    """Per-query cancel/deadline state, polled cooperatively.

    Thread-safe; one token is shared by every pump/retry/spill thread
    of its query.  ``check()`` is the poll: cheap when clean (one
    attribute read + optional deadline compare), raises
    ``QueryCancelled`` once the token fired.  The FIRST raise observes
    ``tpuq_cancel_latency_seconds`` (time from the cancel request — or
    the deadline instant — to the raise) and counts
    ``tpuq_query_cancelled_total{reason}``.
    """

    def __init__(self, query_id: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 poll_ms: float = DEFAULT_POLL_S * 1000.0):
        self.query_id = query_id
        self.poll_s = max(float(poll_ms) / 1000.0, 0.001)
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.reason: Optional[str] = None
        self.detail: str = ""
        self._deadline: Optional[float] = None
        if timeout_ms is not None and timeout_ms > 0:
            self._deadline = time.monotonic() + float(timeout_ms) / 1000.0
        # monotonic instant the cancel became effective (request time
        # for user cancels, the deadline itself for expiries)
        self._effective_at: Optional[float] = None
        self._observed = False
        self.latency_s: Optional[float] = None
        self._waiters: List[threading.Condition] = []
        self._callbacks: List[Callable[[], None]] = []
        # tenant the query runs as — set by the QueryServer at submit;
        # the HBM arbiter charges reservations to it
        self.tenant: str = "default"
        # preemption state (see the module-level state constants)
        self._preempt_state: str = PREEMPT_RUN
        self._preempt_detail: str = ""
        self._preempt_requested_at: Optional[float] = None
        # lease on the suspension: monotonic deadline past which the
        # token force-resumes itself (None = no lease, local requester
        # owns the resume).  Remote/cluster suspends always carry one.
        self._suspend_deadline: Optional[float] = None
        self._resume_event = threading.Event()
        self.suspend_latency_s: Optional[float] = None
        self.preempt_count = 0     # completed suspend->resume cycles

    # -- firing ---------------------------------------------------------

    def cancel(self, reason: str = "user", detail: str = "") -> bool:
        """Fire the token (first cancel wins; returns True on the
        transition).  Wakes every registered waiter and runs every
        registered callback — both OUTSIDE the token lock, so a
        callback/waiter may itself call back into the token."""
        with self._lock:
            if self._event.is_set():
                return False
            self.reason = reason
            self.detail = detail
            self._effective_at = time.monotonic()
            self._event.set()
            # wake suspended parks instantly — their next check() raises
            self._resume_event.set()
            waiters = list(self._waiters)
            callbacks = list(self._callbacks)
        for cv in waiters:
            with cv:
                cv.notify_all()
        for cb in callbacks:
            try:
                cb()
            except Exception:
                pass  # best-effort (e.g. abort to a dead coordinator)
        return True

    def _deadline_fired(self) -> bool:
        if self._deadline is None or time.monotonic() < self._deadline:
            return False
        with self._lock:
            if not self._event.is_set():
                self.reason = "deadline"
                self.detail = "query deadline expired"
                self._effective_at = self._deadline
                self._event.set()
                self._resume_event.set()
                waiters = list(self._waiters)
            else:
                waiters = []
        for cv in waiters:
            with cv:
                cv.notify_all()
        return True

    def cancelled(self) -> bool:
        return self._event.is_set() or self._deadline_fired()

    def check(self) -> None:
        """The poll: raise ``QueryCancelled`` once fired."""
        if not self.cancelled():
            return
        with self._lock:
            if not self._observed:
                self._observed = True
                self.latency_s = max(
                    0.0, time.monotonic() - (self._effective_at
                                             or time.monotonic()))
                first = True
            else:
                first = False
        if first:
            _TM_CANCELLED.inc(self.reason or "user")
            _TM_LATENCY.observe(self.latency_s)
            # flight recorder: the first observation of the fired token
            # is the moment the cancel became effective for the query
            from spark_rapids_tpu.runtime import attribution
            attribution.record_event("cancel", {
                "reason": self.reason or "user",
                "query_id": self.query_id,
                "detail": self.detail,
                "latency_s": round(self.latency_s or 0.0, 6),
            })
        raise QueryCancelled(self.reason or "user", self.query_id,
                             self.detail)

    # -- waiting --------------------------------------------------------

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (None when undeadlined)."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def wait_interval(self, want: Optional[float] = None) -> float:
        """How long a blocking wait may park before it must re-poll:
        min(poll interval, remaining deadline, the caller's own
        bound)."""
        out = self.poll_s
        rem = self.remaining_s()
        if rem is not None:
            out = min(out, max(rem, 0.001))
        if want is not None:
            out = min(out, max(want, 0.0))
        return out

    def sleep(self, seconds: float) -> None:
        """Cancellable sleep: returns after ``seconds`` or raises
        ``QueryCancelled`` within one poll interval of a cancel."""
        deadline = time.monotonic() + max(seconds, 0.0)
        while True:
            self.check()
            self.preempt_point()   # backoff sleeps are yield points too
            rem = deadline - time.monotonic()
            if rem <= 0:
                return
            self._event.wait(self.wait_interval(rem))

    def add_waiter(self, cv: threading.Condition) -> None:
        """Register a condition variable to ``notify_all`` on cancel —
        waiters wake instantly instead of at the next poll tick."""
        with self._lock:
            self._waiters.append(cv)

    def remove_waiter(self, cv: threading.Condition) -> None:
        with self._lock:
            try:
                self._waiters.remove(cv)
            except ValueError:
                pass

    def on_cancel(self, cb: Callable[[], None]) -> Callable[[], None]:
        """Register a callback run (once) on cancel; returns an
        unregister function.  If the token already fired the callback
        runs immediately.  Deadline expiries discovered lazily by a
        poll do NOT run callbacks (there is no thread to run them at
        the deadline instant) — pair callbacks with a poll."""
        with self._lock:
            fired = self._event.is_set()
            if not fired:
                self._callbacks.append(cb)

        def remove():
            with self._lock:
                try:
                    self._callbacks.remove(cb)
                except ValueError:
                    pass

        if fired:
            try:
                cb()
            except Exception:
                pass
        return remove

    # -- preemption -----------------------------------------------------

    @property
    def preempt_state(self) -> str:
        return self._preempt_state

    def preempt_pending(self) -> bool:
        """True while a suspend is requested or in force — resource
        layers (the device semaphore) refuse new admissions for the
        query while this holds."""
        return self._preempt_state in (PREEMPT_SUSPEND_REQUESTED,
                                       PREEMPT_SUSPENDED)

    def suspended(self) -> bool:
        return self._preempt_state == PREEMPT_SUSPENDED

    def request_suspend(self, detail: str = "",
                        ttl_s: Optional[float] = None) -> bool:
        """Ask the query to yield at its next preempt point (first
        request wins; returns True on the RUN/RESUMED ->
        SUSPEND_REQUESTED transition).  A cancelled token cannot be
        suspended — the cancel already reclaims everything.

        ``ttl_s`` leases the suspension: if the requester never resumes
        (or renews via ``refresh_suspend``) within the TTL, the token
        force-resumes itself — a dead requester (executor loss, lease
        expiry, coordinator restart) must never wedge the query in
        SUSPEND_REQUESTED/SUSPENDED."""
        with self._lock:
            if self._event.is_set() or self.preempt_pending():
                return False
            self._preempt_state = PREEMPT_SUSPEND_REQUESTED
            self._preempt_detail = detail
            self._preempt_requested_at = time.monotonic()
            self._suspend_deadline = (
                time.monotonic() + max(float(ttl_s), 0.001)
                if ttl_s is not None else None)
            self._resume_event.clear()
            waiters = list(self._waiters)
        # wake registered waiters (semaphore CVs) so a thread parked in
        # acquire notices the suspend within one tick, not one poll
        for cv in waiters:
            with cv:
                cv.notify_all()
        _TM_PREEMPT_REQ.inc()
        from spark_rapids_tpu.runtime import attribution
        attribution.record_event("preempt", {
            "phase": "suspend_requested", "query_id": self.query_id,
            "detail": detail})
        return True

    def resume(self) -> bool:
        """Let a suspended (or suspend-requested) query run again.
        Sets only the resume event — parked threads wake off it
        directly and semaphore waiters re-poll within their bounded
        wait — so this is safe to call while holding scheduler locks
        (it never takes a foreign condition variable)."""
        with self._lock:
            if not self.preempt_pending():
                return False
            self._preempt_state = PREEMPT_RESUMED
            self.preempt_count += 1
            self._suspend_deadline = None
            self._resume_event.set()
        _TM_PREEMPT_RESUMED.inc()
        from spark_rapids_tpu.runtime import attribution
        attribution.record_event("preempt", {
            "phase": "resumed", "query_id": self.query_id})
        return True

    def refresh_suspend(self, ttl_s: float) -> bool:
        """Renew a leased suspension's TTL (the coordinator re-issues a
        live suspend directive on every heartbeat; a renewal that stops
        arriving lets the lease expire and the wedge guard fire)."""
        with self._lock:
            if not self.preempt_pending():
                return False
            self._suspend_deadline = (time.monotonic()
                                      + max(float(ttl_s), 0.001))
            return True

    def _suspend_expired(self) -> bool:
        dl = self._suspend_deadline
        return dl is not None and time.monotonic() >= dl

    def _force_resume(self) -> bool:
        """Wedge guard: the suspension lease expired without a resume
        or renewal — the requester is gone.  Self-resume so the query
        makes progress again (liveness beats strict capacity: the
        scheduler is told, and may transiently oversubscribe one run
        slot until the next release drains it)."""
        with self._lock:
            if not self.preempt_pending():
                return False
            self._preempt_state = PREEMPT_RESUMED
            self.preempt_count += 1
            self._suspend_deadline = None
            self._resume_event.set()
        _TM_PREEMPT_RESUMED.inc()
        _TM_PREEMPT_FORCE_RESUMED.inc()
        from spark_rapids_tpu.runtime import attribution
        attribution.record_event("preempt", {
            "phase": "force_resumed", "query_id": self.query_id,
            "detail": self._preempt_detail})
        if self.query_id is not None:
            # tell the scheduler that parked our ticket (set by
            # remote_suspend; the global singleton otherwise) so its
            # slot accounting follows the self-resume
            owner = None
            ref = getattr(self, "_suspend_owner", None)
            if ref is not None:
                owner = ref()
            if owner is None:
                from spark_rapids_tpu.runtime import scheduler as _sched
                owner = _sched.peek_scheduler()
            if owner is not None:
                try:
                    owner.notify_force_resumed(self.query_id)
                except Exception:
                    pass
        return True

    def preempt_point(self) -> None:
        """The cooperative yield point, called wherever ``check()`` is
        polled (pump boundaries, semaphore waits, backoff sleeps).
        Fast when clean — one attribute compare.  When a suspend is
        pending the calling thread releases its device permits (via the
        registered suspend providers), the FIRST thread to park spills
        the query's resident device batches through the HBM tiers, and
        every thread waits (cancellably, poll-bounded) for the
        scheduler's resume, then reacquires what it released."""
        if self._preempt_state in (PREEMPT_RUN, PREEMPT_RESUMED):
            return
        self._park_suspended()

    def _park_suspended(self) -> None:
        self.check()
        if self._suspend_expired() and self._force_resume():
            return  # lease already dead on arrival — never park
        states = []
        for suspend_fn, _resume_fn in _SUSPEND_PROVIDERS:
            try:
                states.append(suspend_fn(self))
            except Exception:
                states.append(None)
        first = False
        with self._lock:
            if self._preempt_state == PREEMPT_SUSPEND_REQUESTED:
                self._preempt_state = PREEMPT_SUSPENDED
                first = True
        if first:
            lat = max(0.0, time.monotonic()
                      - (self._preempt_requested_at or time.monotonic()))
            self.suspend_latency_s = lat
            _TM_PREEMPT_SUSPENDED.inc()
            _TM_SUSPEND_LATENCY.observe(lat)
            from spark_rapids_tpu.runtime import attribution
            attribution.record_event("preempt", {
                "phase": "suspended", "query_id": self.query_id,
                "latency_s": round(lat, 6),
                "detail": self._preempt_detail})
            # spill resident device batches so the preemptor inherits
            # the HBM headroom; they rehydrate lazily (bit-identically,
            # CRC-checked) when the query resumes and touches them
            from spark_rapids_tpu.runtime import memory
            mgr = memory.peek_manager()
            if mgr is not None and self.query_id is not None:
                try:
                    mgr.suspend_spill(self.query_id)
                except Exception:
                    pass
        from spark_rapids_tpu.runtime import trace
        tr = trace.current()
        span = (tr.begin("Preempt", "preemptWait")
                if tr is not None else None)
        try:
            while self._preempt_state == PREEMPT_SUSPENDED:
                self.check()
                if self._suspend_expired():
                    self._force_resume()
                    break
                self._resume_event.wait(self.wait_interval())
        finally:
            if span is not None:
                tr.end(span)
            # reacquire in reverse registration order; a provider whose
            # suspend returned None released nothing.  On cancel the
            # reacquire is skipped by the raise — released permits stay
            # released and the hold contexts above know not to
            # double-release (the provider marks what it gave back).
            for (_s, resume_fn), st in zip(reversed(_SUSPEND_PROVIDERS),
                                           reversed(states)):
                if st is not None:
                    resume_fn(self, st)


# ---------------------------------------------------------------------------
# per-THREAD query scope.  PR 8 made this thread-local: concurrent
# queries (the multi-tenant QueryServer runs one per worker thread) each
# own an independent token, while nested executions ON THE SAME THREAD
# still join the outer scope.  Worker threads a query fans out to (the
# partition-pump pool) re-enter the query's scope via ``bind(token)``.
# ---------------------------------------------------------------------------

class _Scope(threading.local):
    token: Optional[CancelToken]
    depth: int

    def __init__(self):
        self.token = None
        self.depth = 0


_SCOPE = _Scope()
_ACTIVE: Dict[int, CancelToken] = {}   # query_id -> token (in-flight)
_ACTIVE_LOCK = threading.Lock()
# tokens of OPEN begin_query scopes, in open order.  When exactly one
# query is running, helper threads the engine spawns without an
# explicit bind() (legacy serial-world pattern) still see its token;
# with several concurrent scopes the ambient view is ambiguous, so
# unbound threads get None and every concurrent path must bind().
_AMBIENT: List[CancelToken] = []


def _thread_token() -> Optional[CancelToken]:
    tok = _SCOPE.token
    if tok is not None:
        return tok
    amb = _AMBIENT
    return amb[0] if len(amb) == 1 else None


def register(token: CancelToken) -> None:
    """Make a pre-created token addressable by ``cancel_query`` /
    ``active_queries`` BEFORE its query executes — the scheduler
    registers tokens at submit time so queued-not-yet-running queries
    can be cancelled and deadline-expired like running ones."""
    if token.query_id is None:
        raise ValueError("cannot register a token without a query_id")
    with _ACTIVE_LOCK:
        _ACTIVE[token.query_id] = token


def unregister(token: CancelToken) -> None:
    """Drop a ``register``-ed token (idempotent; never drops a
    different token that reused the id)."""
    if token.query_id is None:
        return
    with _ACTIVE_LOCK:
        if _ACTIVE.get(token.query_id) is token:
            del _ACTIVE[token.query_id]


def begin_query(query_id: int, conf=None,
                timeout_ms: Optional[float] = None,
                token: Optional[CancelToken] = None
                ) -> Optional[CancelToken]:
    """Open (or join) the calling thread's cancel scope.  Returns the
    token for the OUTERMOST open (the handle ``finish_query`` needs);
    nested executions on the same thread join the outer token and get
    None.  ``timeout_ms`` overrides ``spark.rapids.tpu.query.timeoutMs``;
    <= 0 means no deadline.  ``token`` adopts a pre-created token (the
    scheduler creates tokens at submit time so deadlines tick and
    cancels land while the query is still queued) instead of minting a
    fresh one — its deadline/poll settings are kept as created."""
    _SCOPE.depth += 1
    if _SCOPE.depth > 1:
        return None  # joined this thread's outer query token
    if token is None:
        poll_ms = DEFAULT_POLL_S * 1000.0
        conf_timeout = None
        if conf is not None:
            from spark_rapids_tpu import conf as C
            poll_ms = float(conf.get(C.CANCEL_POLL_MS))
            conf_timeout = float(conf.get(C.QUERY_TIMEOUT_MS))
        eff = timeout_ms if timeout_ms is not None else conf_timeout
        if eff is not None and eff <= 0:
            eff = None
        token = CancelToken(query_id, timeout_ms=eff, poll_ms=poll_ms)
    _SCOPE.token = token
    with _ACTIVE_LOCK:
        _ACTIVE[query_id] = token
        _AMBIENT.append(token)
    return token


def finish_query(token: Optional[CancelToken]) -> None:
    """Close the scope opened by ``begin_query`` (no-op for joiners)."""
    _SCOPE.depth = max(0, _SCOPE.depth - 1)
    if token is None or _SCOPE.depth > 0:
        return
    _SCOPE.token = None
    with _ACTIVE_LOCK:
        if (token.query_id is not None
                and _ACTIVE.get(token.query_id) is token):
            del _ACTIVE[token.query_id]
        try:
            _AMBIENT.remove(token)
        except ValueError:
            pass


@contextlib.contextmanager
def bind(token: Optional[CancelToken]):
    """Run a block under a query's token on a DIFFERENT thread than the
    one that opened the scope — the partition pump binds the submitting
    thread's token into each pool worker so every blocking boundary
    downstream (semaphore, retry backoff, spill IO, shuffle) polls the
    right query's token.  ``bind(None)`` is a no-op scope.  Restores
    the thread's previous scope on exit, so nested binds and
    worker-thread reuse across queries are safe."""
    prev_token, prev_depth = _SCOPE.token, _SCOPE.depth
    if token is not None:
        _SCOPE.token = token
        _SCOPE.depth = prev_depth + 1
    try:
        yield token
    finally:
        _SCOPE.token, _SCOPE.depth = prev_token, prev_depth


def current() -> Optional[CancelToken]:
    """The calling thread's active query token — its own scope, or the
    sole open query's token when exactly one query is running (so
    helper threads spawned without ``bind`` stay cancellable in the
    serial world).  None when out of scope under concurrency."""
    return _thread_token()


def check() -> None:
    """Module-level poll: raise ``QueryCancelled`` if the calling
    thread's query token fired.  Free outside a query scope."""
    tok = _thread_token()
    if tok is not None:
        tok.check()


def sleep(seconds: float) -> None:
    """Cancellable sleep under the calling thread's token; a plain
    sleep outside any query scope."""
    tok = _thread_token()
    if tok is not None:
        tok.sleep(seconds)
    else:
        time.sleep(seconds)  # cancel-exempt: no query scope to cancel


def cancel_query(query_id: int, reason: str = "user",
                 detail: str = "") -> bool:
    """Cancel one in-flight query by id (``session.cancel`` backend).
    Returns False when no such query is active."""
    with _ACTIVE_LOCK:
        tok = _ACTIVE.get(query_id)
    if tok is None:
        return False
    return tok.cancel(reason, detail)


def suspend_query(query_id: int, detail: str = "",
                  ttl_s: Optional[float] = None) -> bool:
    """Request cooperative suspension of one in-flight query (the
    scheduler's preemption arbiter backend; also a chaos-harness hook).
    Returns False when no such query is active or it cannot be
    suspended (already pending, or cancelled).  ``ttl_s`` leases the
    suspension (see ``CancelToken.request_suspend``)."""
    with _ACTIVE_LOCK:
        tok = _ACTIVE.get(query_id)
    if tok is None:
        return False
    return tok.request_suspend(detail, ttl_s=ttl_s)


def resume_query(query_id: int) -> bool:
    """Resume a suspended in-flight query.  Returns False when no such
    query is active or no suspend was pending."""
    with _ACTIVE_LOCK:
        tok = _ACTIVE.get(query_id)
    if tok is None:
        return False
    return tok.resume()


def get_token(query_id: int) -> Optional[CancelToken]:
    """The in-flight token for ``query_id`` (None when not active) —
    observability/harness hook for reading preempt state and latency."""
    with _ACTIVE_LOCK:
        return _ACTIVE.get(query_id)


def active_queries() -> List[int]:
    """Query ids with an open cancel scope, oldest first."""
    with _ACTIVE_LOCK:
        return sorted(_ACTIVE)


def reset() -> None:
    """Test hook: drop any leaked scope state.  Scopes are thread-local
    now, so this clears the CALLING thread's scope plus the process-wide
    active-token table."""
    _SCOPE.token = None
    _SCOPE.depth = 0
    with _ACTIVE_LOCK:
        _ACTIVE.clear()
        del _AMBIENT[:]


def _suspended_now() -> int:
    with _ACTIVE_LOCK:
        return sum(1 for t in _ACTIVE.values() if t.suspended())


TM.REGISTRY.gauge(
    "tpuq_preempt_suspended",
    "in-flight queries currently parked in the SUSPENDED state",
    fn=_suspended_now)
