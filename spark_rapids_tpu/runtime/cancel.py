"""Query lifecycle layer: cooperative cancellation + deadlines.

[REF: Spark's task-kill/interrupt lifecycle (TaskContext.isInterrupted
 polled by long-running tasks) + spill/SpillFramework.scala's
 close-on-task-completion guarantees; GpuSemaphore.scala releases its
 permit on task completion callbacks, cancelled or not.]

The engine can retry (runtime/resilience.py) and detect dead peers
(parallel/rendezvous.py) but a serving stack must also be able to
**stop**: any query can be cancelled (``session.cancel(query_id)``) or
deadlined (``df.collect(timeout_ms=...)`` /
``spark.rapids.tpu.query.timeoutMs``) and the engine returns to a clean
steady state — semaphore permits released, HBM reservations unwound,
spill files unlinked, rendezvous peers fast-aborted.

Design: one ``CancelToken`` per query, opened by the query boundary
(``DataFrame.toArrow``) and **polled at every blocking boundary**:

* exec pump loops (``exec/base.py`` wraps every ``execute``),
* ``DeviceSemaphore.acquire`` (deadline-aware wait a cancel wakes),
* ``RetryPolicy`` backoff sleeps and the OOM retry loop,
* spill write/read (via the guarded retry loop) and shuffle exchange
  materialization,
* rendezvous stage waits (a cancel fast-aborts the epoch so peers are
  not wedged waiting for a cancelled participant).

Cancellation is COOPERATIVE: a blocking wait either registers its
condition variable with the token (woken instantly) or bounds the wait
by ``spark.rapids.tpu.query.cancelPollMs`` — either way a cancel
surfaces as ``QueryCancelled`` within ~2x the poll interval.  The
query boundary then guarantees reclamation (see
``DataFrame._reclaim_cancelled``): ``DeviceMemoryManager.report_leaks()``
returns 0 after every cancelled query.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from spark_rapids_tpu.runtime import telemetry as TM

_TM_CANCELLED = TM.REGISTRY.labeled_counter(
    "tpuq_query_cancelled_total",
    "queries cancelled, by reason (user | deadline)", label="reason")
_TM_LATENCY = TM.REGISTRY.histogram(
    "tpuq_cancel_latency_seconds",
    "cancel-request (or deadline-expiry) to QueryCancelled-raised "
    "latency")

DEFAULT_POLL_S = 0.05


class QueryCancelled(RuntimeError):
    """The query's CancelToken fired.  Non-retryable by design: the
    retry policy, the OOM retry framework, and the rendezvous epoch
    loop all propagate it unchanged (it is not a fault — it is an
    order)."""

    def __init__(self, reason: str, query_id: Optional[int] = None,
                 detail: str = ""):
        msg = f"query {query_id} cancelled ({reason})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.reason = reason          # "user" | "deadline"
        self.query_id = query_id


class CancelToken:
    """Per-query cancel/deadline state, polled cooperatively.

    Thread-safe; one token is shared by every pump/retry/spill thread
    of its query.  ``check()`` is the poll: cheap when clean (one
    attribute read + optional deadline compare), raises
    ``QueryCancelled`` once the token fired.  The FIRST raise observes
    ``tpuq_cancel_latency_seconds`` (time from the cancel request — or
    the deadline instant — to the raise) and counts
    ``tpuq_query_cancelled_total{reason}``.
    """

    def __init__(self, query_id: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 poll_ms: float = DEFAULT_POLL_S * 1000.0):
        self.query_id = query_id
        self.poll_s = max(float(poll_ms) / 1000.0, 0.001)
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.reason: Optional[str] = None
        self.detail: str = ""
        self._deadline: Optional[float] = None
        if timeout_ms is not None and timeout_ms > 0:
            self._deadline = time.monotonic() + float(timeout_ms) / 1000.0
        # monotonic instant the cancel became effective (request time
        # for user cancels, the deadline itself for expiries)
        self._effective_at: Optional[float] = None
        self._observed = False
        self.latency_s: Optional[float] = None
        self._waiters: List[threading.Condition] = []
        self._callbacks: List[Callable[[], None]] = []

    # -- firing ---------------------------------------------------------

    def cancel(self, reason: str = "user", detail: str = "") -> bool:
        """Fire the token (first cancel wins; returns True on the
        transition).  Wakes every registered waiter and runs every
        registered callback — both OUTSIDE the token lock, so a
        callback/waiter may itself call back into the token."""
        with self._lock:
            if self._event.is_set():
                return False
            self.reason = reason
            self.detail = detail
            self._effective_at = time.monotonic()
            self._event.set()
            waiters = list(self._waiters)
            callbacks = list(self._callbacks)
        for cv in waiters:
            with cv:
                cv.notify_all()
        for cb in callbacks:
            try:
                cb()
            except Exception:
                pass  # best-effort (e.g. abort to a dead coordinator)
        return True

    def _deadline_fired(self) -> bool:
        if self._deadline is None or time.monotonic() < self._deadline:
            return False
        with self._lock:
            if not self._event.is_set():
                self.reason = "deadline"
                self.detail = "query deadline expired"
                self._effective_at = self._deadline
                self._event.set()
                waiters = list(self._waiters)
            else:
                waiters = []
        for cv in waiters:
            with cv:
                cv.notify_all()
        return True

    def cancelled(self) -> bool:
        return self._event.is_set() or self._deadline_fired()

    def check(self) -> None:
        """The poll: raise ``QueryCancelled`` once fired."""
        if not self.cancelled():
            return
        with self._lock:
            if not self._observed:
                self._observed = True
                self.latency_s = max(
                    0.0, time.monotonic() - (self._effective_at
                                             or time.monotonic()))
                first = True
            else:
                first = False
        if first:
            _TM_CANCELLED.inc(self.reason or "user")
            _TM_LATENCY.observe(self.latency_s)
        raise QueryCancelled(self.reason or "user", self.query_id,
                             self.detail)

    # -- waiting --------------------------------------------------------

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (None when undeadlined)."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def wait_interval(self, want: Optional[float] = None) -> float:
        """How long a blocking wait may park before it must re-poll:
        min(poll interval, remaining deadline, the caller's own
        bound)."""
        out = self.poll_s
        rem = self.remaining_s()
        if rem is not None:
            out = min(out, max(rem, 0.001))
        if want is not None:
            out = min(out, max(want, 0.0))
        return out

    def sleep(self, seconds: float) -> None:
        """Cancellable sleep: returns after ``seconds`` or raises
        ``QueryCancelled`` within one poll interval of a cancel."""
        deadline = time.monotonic() + max(seconds, 0.0)
        while True:
            self.check()
            rem = deadline - time.monotonic()
            if rem <= 0:
                return
            self._event.wait(self.wait_interval(rem))

    def add_waiter(self, cv: threading.Condition) -> None:
        """Register a condition variable to ``notify_all`` on cancel —
        waiters wake instantly instead of at the next poll tick."""
        with self._lock:
            self._waiters.append(cv)

    def remove_waiter(self, cv: threading.Condition) -> None:
        with self._lock:
            try:
                self._waiters.remove(cv)
            except ValueError:
                pass

    def on_cancel(self, cb: Callable[[], None]) -> Callable[[], None]:
        """Register a callback run (once) on cancel; returns an
        unregister function.  If the token already fired the callback
        runs immediately.  Deadline expiries discovered lazily by a
        poll do NOT run callbacks (there is no thread to run them at
        the deadline instant) — pair callbacks with a poll."""
        with self._lock:
            fired = self._event.is_set()
            if not fired:
                self._callbacks.append(cb)

        def remove():
            with self._lock:
                try:
                    self._callbacks.remove(cb)
                except ValueError:
                    pass

        if fired:
            try:
                cb()
            except Exception:
                pass
        return remove


# ---------------------------------------------------------------------------
# process-wide query scope (mirrors resilience._QueryState: one active
# query scope; nested executions join the outer scope)
# ---------------------------------------------------------------------------

class _Scope:
    def __init__(self):
        self.lock = threading.Lock()
        self.token: Optional[CancelToken] = None
        self.depth = 0


_SCOPE = _Scope()
_ACTIVE: Dict[int, CancelToken] = {}   # query_id -> token (in-flight)
_ACTIVE_LOCK = threading.Lock()


def begin_query(query_id: int, conf=None,
                timeout_ms: Optional[float] = None
                ) -> Optional[CancelToken]:
    """Open (or join) the query's cancel scope.  Returns the token for
    the OUTERMOST open (the handle ``finish_query`` needs); nested
    executions join the outer token and get None.  ``timeout_ms``
    overrides ``spark.rapids.tpu.query.timeoutMs``; <= 0 means no
    deadline."""
    poll_ms = DEFAULT_POLL_S * 1000.0
    conf_timeout = None
    if conf is not None:
        from spark_rapids_tpu import conf as C
        poll_ms = float(conf.get(C.CANCEL_POLL_MS))
        conf_timeout = float(conf.get(C.QUERY_TIMEOUT_MS))
    eff = timeout_ms if timeout_ms is not None else conf_timeout
    if eff is not None and eff <= 0:
        eff = None
    with _SCOPE.lock:
        _SCOPE.depth += 1
        if _SCOPE.depth > 1:
            return None  # joined the outer query's token
        tok = CancelToken(query_id, timeout_ms=eff, poll_ms=poll_ms)
        _SCOPE.token = tok
    with _ACTIVE_LOCK:
        _ACTIVE[query_id] = tok
    return tok


def finish_query(token: Optional[CancelToken]) -> None:
    """Close the scope opened by ``begin_query`` (no-op for joiners)."""
    with _SCOPE.lock:
        _SCOPE.depth = max(0, _SCOPE.depth - 1)
        if token is None or _SCOPE.depth > 0:
            return
        _SCOPE.token = None
    if token.query_id is not None:
        with _ACTIVE_LOCK:
            _ACTIVE.pop(token.query_id, None)


def current() -> Optional[CancelToken]:
    """The active query's token (None outside any query scope)."""
    return _SCOPE.token


def check() -> None:
    """Module-level poll: raise ``QueryCancelled`` if the active
    query's token fired.  Free outside a query scope."""
    tok = _SCOPE.token
    if tok is not None:
        tok.check()


def sleep(seconds: float) -> None:
    """Cancellable sleep under the active token; a plain sleep outside
    any query scope."""
    tok = _SCOPE.token
    if tok is not None:
        tok.sleep(seconds)
    else:
        time.sleep(seconds)  # cancel-exempt: no query scope to cancel


def cancel_query(query_id: int, reason: str = "user",
                 detail: str = "") -> bool:
    """Cancel one in-flight query by id (``session.cancel`` backend).
    Returns False when no such query is active."""
    with _ACTIVE_LOCK:
        tok = _ACTIVE.get(query_id)
    if tok is None:
        return False
    return tok.cancel(reason, detail)


def active_queries() -> List[int]:
    """Query ids with an open cancel scope, oldest first."""
    with _ACTIVE_LOCK:
        return sorted(_ACTIVE)


def reset() -> None:
    """Test hook: drop any leaked scope state."""
    with _SCOPE.lock:
        _SCOPE.token = None
        _SCOPE.depth = 0
    with _ACTIVE_LOCK:
        _ACTIVE.clear()
