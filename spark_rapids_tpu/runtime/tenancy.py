"""Cluster-wide tenancy enforcement — the executor-side agent.

PR 18 built preemptive tenancy inside one process: the scheduler's
arbiter suspends local victims, HBM budgets bound local reservations.
This module is the cross-process half (ISSUE 20 / ROADMAP item 5): a
``TenancyAgent`` rides the executor's rendezvous heartbeat
(``RendezvousClient.start_heartbeat`` piggyback hooks), reporting
per-tenant state up to the coordinator's ``TenancyArbiter`` and
applying the epoch-tagged suspend/resume/shed directives that come
back on the response — so a tenant breaching its cluster share on
executor A is preempted even when the starved waiter sits on
executor B.

Every protocol edge is a failure domain (chaos-injectable as
``tenancy``):

* **Stale/duplicate directives** — every directive carries the
  coordinator generation as its epoch and a unique id; wrong-epoch
  directives are dropped (``tpuq_tenancy_directives_stale_total``),
  duplicate suspends act as lease renewals, duplicate resumes are
  no-ops.  A directive racing a cancel always loses: the scheduler's
  ``remote_suspend`` refuses cancelled tokens.
* **Executor loss / coordinator restart mid-suspend** — a remote
  suspend is a LEASE (``tenancy.suspendTtlMs``, default 2x
  ``preempt.graceMs``): the coordinator renews it every heartbeat
  while warranted; when renewals stop, the token force-resumes itself
  (``tpuq_preempt_force_resumed_total``) and the scheduler's
  accounting follows — a directive can delay work, never wedge it.
* **Heartbeat flaps** — after ``tenancy.degradedAfterMisses``
  consecutive misses the agent drops to local-only enforcement
  (``tpuq_tenancy_degraded_total``); the first heartbeat that
  round-trips again re-syncs (``tpuq_tenancy_resyncs_total``):
  applied-directive memory clears, dead leases prune, and the
  arbiter's fresh decisions converge within a few heartbeats.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

from spark_rapids_tpu.runtime import telemetry as TM

_TM_DEGRADED = TM.REGISTRY.counter(
    "tpuq_tenancy_degraded_total",
    "times an executor dropped to local-only tenancy enforcement "
    "after consecutive heartbeat misses (coordinator down or "
    "unreachable)")
_TM_DIRECTIVES = TM.REGISTRY.labeled_counter(
    "tpuq_tenancy_directives_total",
    "cluster arbiter directives applied by this executor, by kind "
    "(suspend | resume | shed | unshed)", label="kind")
_TM_STALE = TM.REGISTRY.counter(
    "tpuq_tenancy_directives_stale_total",
    "directives dropped as stale (wrong epoch — issued by a previous "
    "coordinator generation) or targeting a finished/cancelled query")
_TM_RESYNC = TM.REGISTRY.counter(
    "tpuq_tenancy_resyncs_total",
    "agent re-syncs with the coordinator after a miss streak or an "
    "epoch (generation) change — coordinator restart recovery")

#: bounded memory of applied directive ids (idempotency window)
_APPLIED_CAP = 512


class TenancyAgent:
    """One executor's end of the cluster tenancy protocol.

    Wire it into the heartbeat:
        agent = TenancyAgent(scheduler, conf=conf)
        client.start_heartbeat(period_s, payload_fn=agent.payload,
                               on_response=agent.on_heartbeat,
                               on_miss=agent.on_miss)
    """

    def __init__(self, scheduler, conf=None):
        from spark_rapids_tpu import conf as C
        self.sched = scheduler
        # disabled agents stay wireable (the heartbeat hooks are
        # no-ops): enforcement falls back to process-local only
        self.enabled = (bool(conf.get(C.TENANCY_ENABLED))
                        if conf is not None
                        else bool(C.TENANCY_ENABLED.default))
        ttl_ms = (float(conf.get(C.TENANCY_SUSPEND_TTL_MS))
                  if conf is not None
                  else float(C.TENANCY_SUSPEND_TTL_MS.default))
        if ttl_ms <= 0:
            ttl_ms = 2.0 * scheduler.preempt_grace_s * 1000.0
        self.suspend_ttl_s = max(ttl_ms / 1000.0, 0.001)
        self.degraded_after = (int(conf.get(C.TENANCY_DEGRADED_AFTER))
                               if conf is not None
                               else C.TENANCY_DEGRADED_AFTER.default)
        self._lock = threading.Lock()
        self._applied: "OrderedDict[str, str]" = OrderedDict()
        self._holds: Dict[int, str] = {}   # query_id -> directive id
        self._breaches: Dict[str, int] = {}  # pending HBM-breach relays
        self._epoch: Optional[int] = None
        self._misses = 0
        self.degraded = False
        # observability (read by the soak harness / bench)
        self.applied: Dict[str, int] = {"suspend": 0, "resume": 0,
                                        "shed": 0, "unshed": 0}
        self.stale = 0
        self.resyncs = 0
        self.degraded_entries = 0
        self.last_fanout_s: Optional[float] = None
        self.max_fanout_s = 0.0

    # -- heartbeat piggyback -------------------------------------------

    def payload(self) -> dict:
        """The per-tenant report riding this heartbeat: scheduler
        depth/starvation state, live HBM bytes per tenant, and any
        HBM-breach relays since the last beat."""
        if not self.enabled:
            return {}
        rep = self.sched.local_tenancy_report()
        from spark_rapids_tpu.runtime import memory
        mgr = memory.peek_manager()
        if mgr is not None:
            try:
                usage = mgr.tenant_usage()
            except Exception:
                usage = {}
            for name, t in rep.get("tenants", {}).items():
                t["hbm_bytes"] = int(usage.get(name, 0))
        with self._lock:
            self._prune_holds_locked()
            rep["held"] = sorted(self._holds)
            if self._breaches:
                rep["breaches"] = dict(self._breaches)
                self._breaches.clear()
        return rep

    def on_heartbeat(self, resp: dict) -> None:
        """Coordinator replied: leave degraded mode, re-sync on an
        epoch (generation) change or after a miss streak, then apply
        the pending directives."""
        if not self.enabled:
            return
        if not resp.get("ok"):
            self.on_miss()   # declared dead — must re-register to rejoin
            return
        epoch = resp.get("tenancy_epoch")
        with self._lock:
            resync = (self._misses >= 1
                      or (self._epoch is not None and epoch is not None
                          and int(epoch) != self._epoch))
            self._misses = 0
            self.degraded = False
            if epoch is not None:
                self._epoch = int(epoch)
            if resync:
                # a restarted coordinator re-issues what it still
                # wants; everything else must not replay from memory
                self._applied.clear()
                self._prune_holds_locked()
                self.resyncs += 1
        if resync:
            _TM_RESYNC.inc()
        from spark_rapids_tpu.runtime import resilience as R
        try:
            R.INJECTOR.on("tenancy")
        except R.InjectedDeviceError:
            # injected directive-path fault: drop this round's
            # directives — suspends are leases the arbiter renews next
            # beat, so the protocol self-heals
            return
        for d in resp.get("directives") or ():
            self.apply_directive(d)

    def on_miss(self) -> None:
        """Heartbeat could not reach the coordinator."""
        with self._lock:
            self._misses += 1
            trip = (self._misses >= self.degraded_after
                    and not self.degraded)
            if trip:
                self.degraded = True
                self.degraded_entries += 1
        if trip:
            _TM_DEGRADED.inc()
            TM.REGISTRY.record_health({
                "severity": "WARN", "check": "tenancy_degraded",
                "value": self._misses, "threshold": self.degraded_after,
                "detail": "coordinator unreachable — falling back to "
                          "local-only tenancy enforcement"})

    # -- directives -----------------------------------------------------

    def apply_directive(self, d: dict) -> bool:
        """Apply one epoch-tagged directive; idempotent (duplicate
        suspends renew the lease, duplicate resumes/sheds no-op) and
        stale-safe (wrong epoch drops).  Returns True if it took
        effect.  Cancel always wins a directive-vs-cancel race."""
        from spark_rapids_tpu.runtime import cancel as CN
        kind = str(d.get("kind", ""))
        did = str(d.get("id", ""))
        epoch = d.get("epoch")
        qid = d.get("query_id")
        tenant = str(d.get("tenant", "default"))
        with self._lock:
            if (epoch is not None and self._epoch is not None
                    and int(epoch) != self._epoch):
                self.stale += 1
                stale = True
            else:
                stale = False
            dup = did in self._applied
        if stale:
            _TM_STALE.inc()
            return False
        ttl = max(self.suspend_ttl_s, float(d.get("ttl_ms", 0)) / 1000.0)
        if kind == "suspend":
            if dup:
                # lease renewal — push the token's force-resume
                # deadline out another TTL
                tok = CN.get_token(qid) if qid is not None else None
                return bool(tok is not None and tok.refresh_suspend(ttl))
            ok = (qid is not None
                  and self.sched.remote_suspend(
                      qid, d.get("detail") or "cluster arbiter "
                      "directive", ttl_s=ttl))
            self._record(did, kind, ok)
            if ok:
                with self._lock:
                    self._holds[qid] = did
                issued = d.get("issued_wall")
                if issued is not None:
                    lat = max(0.0, time.time() - float(issued))
                    self.last_fanout_s = lat
                    self.max_fanout_s = max(self.max_fanout_s, lat)
            else:
                # target finished or cancelled first — cancel wins
                _TM_STALE.inc()
                with self._lock:
                    self.stale += 1
            return ok
        if kind == "resume":
            if dup:
                return False
            ok = qid is not None and self.sched.remote_resume(qid)
            self._record(did, kind, ok)
            with self._lock:
                self._holds.pop(qid, None)
            return ok
        if kind in ("shed", "unshed"):
            if dup:
                return False
            self.sched.set_cluster_shed(tenant, kind == "shed")
            self._record(did, kind, True)
            return True
        return False

    def _record(self, did: str, kind: str, ok: bool) -> None:
        with self._lock:
            self._applied[did] = kind
            while len(self._applied) > _APPLIED_CAP:
                self._applied.popitem(last=False)
            if ok:
                self.applied[kind] = self.applied.get(kind, 0) + 1
        if ok:
            _TM_DIRECTIVES.inc(kind)

    def _prune_holds_locked(self) -> None:
        # drop leases whose token already resumed (wedge guard fired,
        # query finished, or cancel won) — callers hold self._lock
        from spark_rapids_tpu.runtime import cancel as CN
        for qid in list(self._holds):
            tok = CN.get_token(qid)
            if tok is None or not tok.preempt_pending():
                del self._holds[qid]

    # -- HBM breach relay ----------------------------------------------

    def notify_breach(self, tenant: str) -> None:
        """Memory-arbiter hook: a tenant breached its HBM budget and
        local preemption found no victim — relay it on the next
        heartbeat so the cluster arbiter can suspend the tenant's
        largest-runtime query on another executor."""
        with self._lock:
            self._breaches[tenant] = self._breaches.get(tenant, 0) + 1

    def stats(self) -> dict:
        with self._lock:
            return {"applied": dict(self.applied),
                    "stale": self.stale,
                    "resyncs": self.resyncs,
                    "degraded": self.degraded,
                    "degraded_entries": self.degraded_entries,
                    "live_holds": len(self._holds),
                    "last_fanout_s": self.last_fanout_s,
                    "max_fanout_s": self.max_fanout_s}


# -- process singleton (the memory arbiter's relay target) ----------------

_agent: Optional[TenancyAgent] = None
_agent_lock = threading.Lock()


def set_agent(agent: Optional[TenancyAgent]) -> None:
    global _agent
    with _agent_lock:
        _agent = agent


def peek_agent() -> Optional[TenancyAgent]:
    """The process agent if one is wired up — never creates (an
    executor without the cluster protocol stays purely local)."""
    return _agent


def reset_agent() -> None:
    set_agent(None)
