"""Back-compat shim over the unified resilience layer.

The original two-chokepoint fault injector (kernel execute + D2H
transfer) grew into ``runtime/resilience.py``'s nine-domain registry
with a conf-driven retry policy and circuit breakers.  This module
keeps the historical import surface alive:

* ``INJECTOR`` — the process injector (now the domain registry).
* ``InjectedDeviceError`` — raised by armed chokepoints.
* ``configure_from_conf`` — arming entry point (legacy
  ``injectExecuteErrorAt``/``injectTransferErrorAt``/
  ``injectTransientCount`` keys still map onto the execute/transfer
  domains).
* ``retry_device_call`` — retries transient injected faults with
  attempts taken from ``spark.rapids.tpu.retry.maxAttempts`` (the old
  hardcoded ``max_attempts=2`` ignored that conf).

[REF: spark-rapids-jni :: src/main/cpp/faultinj/; SURVEY §2.2 N15]
"""

from __future__ import annotations

from spark_rapids_tpu.runtime.resilience import (  # noqa: F401
    INJECTOR, FaultInjector, InjectedDeviceError, TerminalDeviceError,
    configure_from_conf, retry_device_call)

__all__ = ["INJECTOR", "FaultInjector", "InjectedDeviceError",
           "TerminalDeviceError", "configure_from_conf",
           "retry_device_call"]
