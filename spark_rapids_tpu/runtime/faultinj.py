"""Device-call fault injection — the resilience test shim.

[REF: spark-rapids-jni :: src/main/cpp/faultinj/ — an LD_PRELOAD CUDA
 interceptor forcing errors for resilience tests; SURVEY §2.2 N15] —
the TPU analog intercepts the engine's two device-call chokepoints
(kernel execution via runtime/kernel_cache.py, device→host transfer via
columnar/column.py) and raises a configured fault at the Nth call:

* ``spark.rapids.tpu.test.injectExecuteErrorAt`` — from the Nth kernel
  call on, raise ``InjectedDeviceError``: ``injectTransientCount``
  transient fires (proving retry recovery, or retry exhaustion when the
  budget exceeds the attempts), else one terminal fire.
* ``spark.rapids.tpu.test.injectTransferErrorAt`` — same for D2H
  transfers.

State is process-global (like the reference's interceptor); an armed
chokepoint self-disarms once its fires are spent, and a conf without
injection keys never touches another session's armed state.
"""

from __future__ import annotations

import threading
from typing import Optional


class InjectedDeviceError(RuntimeError):
    """A fault-injected device error (execute or transfer)."""

    def __init__(self, where: str, nth: int, transient: bool):
        super().__init__(
            f"injected {where} error at call #{nth} "
            f"({'transient' if transient else 'terminal'})")
        self.where = where
        self.transient = transient


class _Injector:
    """Firing model: once a chokepoint's call count reaches its
    configured N it starts firing.  With ``transient_count == 0`` the
    fire is terminal and the chokepoint disarms.  With a budget K > 0,
    K consecutive calls fire transient and then the chokepoint disarms
    — K = 1 proves single-retry recovery; K ≥ the engine's retry
    attempts models a persistent fault (retries exhaust and the
    transient error propagates).  Disarming on exhaustion means an
    armed injection never leaks into later queries."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._config = None
            self._exec_at = -1
            self._transfer_at = -1
            self._transient_budget = 0
            self._exec_count = 0
            self._transfer_count = 0
            self._transients_fired = 0

    def configure(self, exec_at: int, transfer_at: int,
                  transient_count: int) -> None:
        with self._lock:
            self._config = (int(exec_at), int(transfer_at),
                            int(transient_count))
            self._exec_at = int(exec_at)
            self._transfer_at = int(transfer_at)
            self._transient_budget = int(transient_count)
            self._exec_count = 0
            self._transfer_count = 0
            self._transients_fired = 0

    @property
    def armed(self) -> bool:
        return self._exec_at >= 0 or self._transfer_at >= 0

    def _disarm(self, where: str) -> None:
        if where == "execute":
            self._exec_at = -1
        else:
            self._transfer_at = -1

    def _fire(self, where: str, n: int) -> None:
        transient = self._transients_fired < self._transient_budget
        if transient:
            self._transients_fired += 1
            if self._transients_fired >= self._transient_budget:
                self._disarm(where)  # budget spent: later calls pass
        else:
            self._disarm(where)  # terminal
        raise InjectedDeviceError(where, n, transient)

    def on_execute(self) -> None:
        if self._exec_at < 0:
            return
        with self._lock:
            self._exec_count += 1
            if 0 <= self._exec_at <= self._exec_count:
                self._fire("execute", self._exec_count)

    def on_transfer(self) -> None:
        if self._transfer_at < 0:
            return
        with self._lock:
            self._transfer_count += 1
            if 0 <= self._transfer_at <= self._transfer_count:
                self._fire("transfer", self._transfer_count)


INJECTOR = _Injector()


def configure_from_conf(conf) -> None:
    """Arm from an injection-carrying conf; reconfigure only when the
    requested config CHANGES.  A conf with the keys at their defaults
    never touches the injector — concurrent clean sessions (planning,
    explain()) must not disarm another session's armed injection.
    Disarm happens via terminal self-disarm or ``INJECTOR.reset()``."""
    from spark_rapids_tpu import conf as C
    ex = int(conf.get(C.INJECT_EXECUTE_AT))
    tr = int(conf.get(C.INJECT_TRANSFER_AT))
    tc = int(conf.get(C.INJECT_TRANSIENT_COUNT))
    if ex < 0 and tr < 0:
        return
    # reconfigure on a CHANGED config, or re-arm an identical config
    # whose fires are fully spent (per-query determinism) — but never
    # while any chokepoint of the current config is still armed, which
    # would reset another in-flight query's injection pattern
    if INJECTOR._config != (ex, tr, tc) or not INJECTOR.armed:
        INJECTOR.configure(ex, tr, tc)


def retry_device_call(fn, *args, max_attempts: int = 2, **kw):
    """Run a device call, retrying transient injected faults once —
    the engine-side policy the reference's faultinj exercises
    [REF: SURVEY §5.3 failure-detection policy]."""
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kw)
        except InjectedDeviceError as e:
            if not e.transient or attempt >= max_attempts:
                raise
