"""Operator-kernel executable cache.

THE TPU-idiom mechanism (SURVEY §7): each physical operator's device work
is one jitted function, cached by the operator's *structural fingerprint*
(expression tree, literals, dtypes, options); jax's own jit cache then
keys on input shapes, so each (op, schema, bucket) pair compiles exactly
once and stays hot across queries — the analog of cuDF's precompiled
kernels, and essential on TPU where eager dispatch means one XLA
compilation per arithmetic op.

Three layers, innermost first: jax's jit cache (per shape bucket), this
module's fingerprint cache (per op structure), and — when
``spark.rapids.tpu.kernel.cacheDir`` is set — jax's on-disk
compilation cache (per machine, survives process restarts; see
``configure_persistent_cache``).  The shape plane (runtime/shapes.py)
bounds the bucket axis so all three stay small.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import jax

from spark_rapids_tpu.runtime import resilience as R
from spark_rapids_tpu.runtime import telemetry as TM
from spark_rapids_tpu.runtime import trace

_CACHE: Dict[tuple, Callable] = {}
# partitions pump on a thread pool: without a lock, racing threads each
# build their own jit wrapper for the same key and XLA compiles twice
_CACHE_LOCK = threading.Lock()

_TM_HITS = TM.REGISTRY.counter(
    "tpuq_kernel_cache_hits_total",
    "cached_kernel lookups served by the fingerprint cache")
_TM_MISSES = TM.REGISTRY.counter(
    "tpuq_kernel_cache_misses_total",
    "cached_kernel lookups that built a new jit wrapper")
_TM_COMPILES = TM.REGISTRY.counter(
    "tpuq_kernel_compile_total", "XLA compilations observed")
_TM_COMPILE_S = TM.REGISTRY.counter(
    "tpuq_kernel_compile_seconds_total",
    "seconds spent in dispatches that triggered an XLA compile")
TM.REGISTRY.gauge(
    "tpuq_kernel_cache_size", "live cached kernel wrappers",
    fn=lambda: len(_CACHE))


def fingerprint(v) -> object:
    """Structural, hashable key for expression/aggregate trees."""
    from spark_rapids_tpu.columnar import dtypes as T

    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        if isinstance(v, T.DataType):
            return v.simple_name
        return (type(v).__name__,) + tuple(
            fingerprint(getattr(v, f.name)) for f in dataclasses.fields(v))
    if isinstance(v, (list, tuple)):
        return tuple(fingerprint(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, fingerprint(x)) for k, x in v.items()))
    return repr(v)


def _jit_once(fn: Callable) -> Callable:
    """jit ``fn`` unless the builder already did.

    SPMD exchange programs come out of their builders pre-jitted with
    ``donate_argnums`` — re-wrapping them would trace THROUGH the inner
    pjit and silently drop the donation annotation (the outer jit's
    donation set, empty, is the one that counts).  ``_cache_size`` is
    the jit-wrapper attribute the compile detector below already keys
    on, so its presence is the reliable already-jitted signal."""
    return fn if hasattr(fn, "_cache_size") else jax.jit(fn)


def _build_wrapper(key: tuple, builder: Callable[[], Callable]):
    """jit the built kernel through the ``compile`` failure domain.

    The chokepoint fires at jit-wrapper construction (the cache-miss
    boundary every XLA compile passes).  Degradation returns the raw
    un-jitted builder output — eager per-op dispatch instead of one
    compiled executable."""
    if not R.active():
        return _jit_once(builder())

    def attempt():
        R.INJECTOR.on("compile")
        return _jit_once(builder())

    def degrade():
        return builder()

    return R.run_guarded("compile", attempt, op=_op_label(key),
                         degrade=degrade)


def _op_label(key: tuple) -> str:
    head = key[0] if key else "kernel"
    return head if isinstance(head, str) else repr(head)


def cached_kernel(key: tuple, builder: Callable[[], Callable]) -> Callable:
    """Return the jitted kernel for key, building+jitting it on first use.

    jax.jit itself is lazy (tracing happens at first call), so holding the
    lock across build+insert is cheap.  Every call passes the fault
    injector's execute chokepoint [REF: faultinj analog, SURVEY N15] —
    an attribute check when disarmed, a policy-guarded call when armed
    (or when this op's breaker is already open).  Exhausted retries
    degrade to re-running the op's builder eagerly, outside the failing
    compiled executable."""
    with _CACHE_LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _TM_HITS.inc()
            return fn
        _TM_MISSES.inc()
        jfn = _build_wrapper(key, builder)

        def _call(args, kw, __jfn=jfn, __key=key, __builder=builder):
            if not R.active():
                return __jfn(*args, **kw)

            def attempt():
                R.INJECTOR.on("execute")
                return __jfn(*args, **kw)

            def degrade():
                return __builder()(*args, **kw)

            return R.run_guarded("execute", attempt,
                                 op=_op_label(__key), degrade=degrade)

        def fn(*args, __jfn=jfn, **kw):
            tr = trace.current()
            # jax.jit compiles lazily at first call per shape bucket;
            # the cache-size delta distinguishes an XLA compile from a
            # hot dispatch — compiles get their own span stage and the
            # registry's compile count/time
            before = (__jfn._cache_size()
                      if hasattr(__jfn, "_cache_size") else None)
            if tr is None and before is None:
                return _call(args, kw)
            t0 = time.perf_counter()
            sp = tr.begin("Kernel", "kernel") if tr is not None else None
            try:
                return _call(args, kw)
            finally:
                if (before is not None
                        and __jfn._cache_size() > before):
                    _TM_COMPILES.inc()
                    _TM_COMPILE_S.inc(time.perf_counter() - t0)
                    if sp is not None:
                        sp.stage = "compile"
                if sp is not None:
                    tr.end(sp)

        _CACHE[key] = fn
        return fn


def cache_stats() -> Tuple[int,]:
    return (len(_CACHE),)


def compile_snapshot() -> Tuple[int, float]:
    """(compile count, compile seconds) observed so far — the
    before/after pair bench.py and ``session.warmup`` diff to attribute
    compiles to a phase (cold run, warm run, warmup)."""
    return (int(_TM_COMPILES.value), float(_TM_COMPILE_S.value))


# ---------------------------------------------------------------------------
# Persistent compilation cache (spark.rapids.tpu.kernel.cacheDir)
# ---------------------------------------------------------------------------
#
# The in-process layers above make each (op, schema, bucket) compile once
# per PROCESS; this layer makes it compile once per MACHINE.  It enables
# jax's on-disk compilation cache under the conf'd directory, so a fresh
# QueryServer process whose cacheDir was warmed by a previous run (or by
# ``session.warmup``) loads executables from disk instead of invoking
# XLA on the hot path.

MANIFEST_NAME = "tpuq_cache_manifest.json"
_PERSISTENT_DIR: Optional[str] = None


def _cache_versions() -> Dict[str, str]:
    """The compatibility tuple a cache directory is valid for."""
    import jaxlib

    from spark_rapids_tpu import __version__ as engine_version
    return {"format": "1", "jax": jax.__version__,
            "jaxlib": jaxlib.__version__, "engine": engine_version}


def _sync_manifest(cache_dir: str) -> bool:
    """Validate ``cache_dir`` against the current versions.

    Returns True when existing entries were kept (manifest matched).
    On mismatch — a different jax/jaxlib/engine wrote them, and XLA's
    serialized executables make no cross-version promises — every entry
    is dropped and the manifest is rewritten for this build."""
    import json
    import os
    import shutil
    path = os.path.join(cache_dir, MANIFEST_NAME)
    want = _cache_versions()
    try:
        with open(path) as f:
            have = json.load(f)
    except (OSError, ValueError):
        have = None
    if have == want:
        return True
    for name in os.listdir(cache_dir):
        if name == MANIFEST_NAME:
            continue
        p = os.path.join(cache_dir, name)
        try:
            if os.path.isdir(p):
                shutil.rmtree(p)
            else:
                os.unlink(p)
        except OSError:
            pass  # a torn delete only costs one stale entry re-check
    with open(path, "w") as f:
        json.dump(want, f)
    return False


def configure_persistent_cache(conf) -> Optional[str]:
    """Point jax's on-disk compilation cache at kernel.cacheDir.

    Called at session init (after the backend is resolved).  An empty
    cacheDir leaves the runtime/device.py env-var default in charge.
    On the XLA:CPU backend this is a hard no-op regardless of conf —
    CPU AOT cache entries carry target pseudo-features the loader's
    host check rejects, and reading one SEGFAULTS the process (see
    runtime/device.py) — TPU compile times are what the cache is for.
    Returns the active directory, or None when disabled."""
    import os

    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu.runtime.device import (
        _machine_fingerprint, ensure_initialized)
    global _PERSISTENT_DIR
    cache_dir = str(conf.get(C.KERNEL_CACHE_DIR)).strip()
    if not cache_dir:
        return _PERSISTENT_DIR
    ensure_initialized()
    if jax.default_backend() == "cpu":
        return None
    cache_dir = os.path.join(os.path.expanduser(cache_dir),
                             _machine_fingerprint())
    os.makedirs(cache_dir, exist_ok=True)
    _sync_manifest(cache_dir)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # persist EVERY executable, not only slow ones: the warm-restart
    # contract is zero hot-path compiles, and a 50 ms compile skipped
    # from disk is still a compile the storm detector would count
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    _PERSISTENT_DIR = cache_dir
    return cache_dir


def persistent_cache_dir() -> Optional[str]:
    """The conf-selected on-disk cache directory, when one is active."""
    return _PERSISTENT_DIR


def clear() -> None:
    """Drop every cached kernel wrapper AND jax's compiled executables.

    Needed by long single-process runs on the CPU platform: XLA:CPU
    JIT-compiled executables accumulate in code memory, and past a few
    hundred live programs LLVM's emitter can crash the process during a
    NEW compilation (observed as a SIGSEGV inside
    ``backend_compile_and_load`` late in the test suite).  Clearing
    between test modules bounds live executables; kernels lazily
    recompile on next use."""
    with _CACHE_LOCK:
        _CACHE.clear()
    jax.clear_caches()
