"""Operator-kernel executable cache.

THE TPU-idiom mechanism (SURVEY §7): each physical operator's device work
is one jitted function, cached by the operator's *structural fingerprint*
(expression tree, literals, dtypes, options); jax's own jit cache then
keys on input shapes, so each (op, schema, bucket) pair compiles exactly
once and stays hot across queries — the analog of cuDF's precompiled
kernels, and essential on TPU where eager dispatch means one XLA
compilation per arithmetic op.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, Tuple

import jax

from spark_rapids_tpu.runtime import resilience as R
from spark_rapids_tpu.runtime import telemetry as TM
from spark_rapids_tpu.runtime import trace

_CACHE: Dict[tuple, Callable] = {}
# partitions pump on a thread pool: without a lock, racing threads each
# build their own jit wrapper for the same key and XLA compiles twice
_CACHE_LOCK = threading.Lock()

_TM_HITS = TM.REGISTRY.counter(
    "tpuq_kernel_cache_hits_total",
    "cached_kernel lookups served by the fingerprint cache")
_TM_MISSES = TM.REGISTRY.counter(
    "tpuq_kernel_cache_misses_total",
    "cached_kernel lookups that built a new jit wrapper")
_TM_COMPILES = TM.REGISTRY.counter(
    "tpuq_kernel_compile_total", "XLA compilations observed")
_TM_COMPILE_S = TM.REGISTRY.counter(
    "tpuq_kernel_compile_seconds_total",
    "seconds spent in dispatches that triggered an XLA compile")
TM.REGISTRY.gauge(
    "tpuq_kernel_cache_size", "live cached kernel wrappers",
    fn=lambda: len(_CACHE))


def fingerprint(v) -> object:
    """Structural, hashable key for expression/aggregate trees."""
    from spark_rapids_tpu.columnar import dtypes as T

    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        if isinstance(v, T.DataType):
            return v.simple_name
        return (type(v).__name__,) + tuple(
            fingerprint(getattr(v, f.name)) for f in dataclasses.fields(v))
    if isinstance(v, (list, tuple)):
        return tuple(fingerprint(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, fingerprint(x)) for k, x in v.items()))
    return repr(v)


def _build_wrapper(key: tuple, builder: Callable[[], Callable]):
    """jit the built kernel through the ``compile`` failure domain.

    The chokepoint fires at jit-wrapper construction (the cache-miss
    boundary every XLA compile passes).  Degradation returns the raw
    un-jitted builder output — eager per-op dispatch instead of one
    compiled executable."""
    if not R.active():
        return jax.jit(builder())

    def attempt():
        R.INJECTOR.on("compile")
        return jax.jit(builder())

    def degrade():
        return builder()

    return R.run_guarded("compile", attempt, op=_op_label(key),
                         degrade=degrade)


def _op_label(key: tuple) -> str:
    head = key[0] if key else "kernel"
    return head if isinstance(head, str) else repr(head)


def cached_kernel(key: tuple, builder: Callable[[], Callable]) -> Callable:
    """Return the jitted kernel for key, building+jitting it on first use.

    jax.jit itself is lazy (tracing happens at first call), so holding the
    lock across build+insert is cheap.  Every call passes the fault
    injector's execute chokepoint [REF: faultinj analog, SURVEY N15] —
    an attribute check when disarmed, a policy-guarded call when armed
    (or when this op's breaker is already open).  Exhausted retries
    degrade to re-running the op's builder eagerly, outside the failing
    compiled executable."""
    with _CACHE_LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _TM_HITS.inc()
            return fn
        _TM_MISSES.inc()
        jfn = _build_wrapper(key, builder)

        def _call(args, kw, __jfn=jfn, __key=key, __builder=builder):
            if not R.active():
                return __jfn(*args, **kw)

            def attempt():
                R.INJECTOR.on("execute")
                return __jfn(*args, **kw)

            def degrade():
                return __builder()(*args, **kw)

            return R.run_guarded("execute", attempt,
                                 op=_op_label(__key), degrade=degrade)

        def fn(*args, __jfn=jfn, **kw):
            tr = trace.current()
            # jax.jit compiles lazily at first call per shape bucket;
            # the cache-size delta distinguishes an XLA compile from a
            # hot dispatch — compiles get their own span stage and the
            # registry's compile count/time
            before = (__jfn._cache_size()
                      if hasattr(__jfn, "_cache_size") else None)
            if tr is None and before is None:
                return _call(args, kw)
            t0 = time.perf_counter()
            sp = tr.begin("Kernel", "kernel") if tr is not None else None
            try:
                return _call(args, kw)
            finally:
                if (before is not None
                        and __jfn._cache_size() > before):
                    _TM_COMPILES.inc()
                    _TM_COMPILE_S.inc(time.perf_counter() - t0)
                    if sp is not None:
                        sp.stage = "compile"
                if sp is not None:
                    tr.end(sp)

        _CACHE[key] = fn
        return fn


def cache_stats() -> Tuple[int,]:
    return (len(_CACHE),)


def clear() -> None:
    """Drop every cached kernel wrapper AND jax's compiled executables.

    Needed by long single-process runs on the CPU platform: XLA:CPU
    JIT-compiled executables accumulate in code memory, and past a few
    hundred live programs LLVM's emitter can crash the process during a
    NEW compilation (observed as a SIGSEGV inside
    ``backend_compile_and_load`` late in the test suite).  Clearing
    between test modules bounds live executables; kernels lazily
    recompile on next use."""
    with _CACHE_LOCK:
        _CACHE.clear()
    jax.clear_caches()
