"""Multi-tenant admission control and fair query scheduling.

The serving tier's gate in front of the whole engine: many callers
submit queries across named *tenants*; this module decides — before any
plan executes or reserves a byte of HBM — whether each submission is
admitted, queued, or shed, and in what order queued queries get one of
the ``maxConcurrentQueries`` run slots.

Three layers, checked in order:

1. **Load shedding** (service-wide watermarks, conf family
   ``spark.rapids.tpu.scheduler.shed.*``): total depth (queued +
   running), host spill-tier pressure
   (``DeviceMemoryManager.spill_pressure``), and device-admission
   saturation (``(holders + waiting) / permits`` on the
   ``DeviceSemaphore``).  A breach rejects the submission with
   ``QueryRejected(reason='shed_*')``, bumps
   ``tpuq_admission_shed_total{tenant=...}`` and records a health WARN
   — the service defends itself BEFORE the HBM arbiter starts
   thrashing the disk tier.
2. **Per-tenant quotas**: ``maxQueued`` rejects
   (``reason='tenant_queue_full'``); ``maxInFlight`` and the HBM share
   never reject — they bound how many of the tenant's queries may RUN
   at once, so excess submissions queue.  The HBM share is enforced as
   a fraction of the global run slots (each running query may reserve
   up to the full HBM pool, so capping a tenant's concurrent run slots
   caps its share of device-memory pressure).
3. **Fair dispatch**: weighted deficit round-robin across tenants —
   each refill round adds ``weight`` credit to every backlogged
   tenant, one run-slot grant costs one credit — with strict priority
   lanes inside a tenant (higher ``priority`` first, FIFO within a
   lane).  A weight-2 tenant drains twice as fast as a weight-1 tenant
   under contention, and no backlogged tenant starves: its deficit
   grows every round until it wins one.

Cancellation composes: a queued ticket's worker blocks in
``acquire()`` polling its ``CancelToken``, so ``session.cancel`` and
deadline expiry surface ``QueryCancelled`` within ~2x the poll
interval *without* the query ever being admitted, and the vacated
queue entry is dispatched past immediately.

``device_hold`` at the bottom is THE sanctioned path to the
``DeviceSemaphore`` — the ``scheduler-bypass`` tier-1 lint rule fails
any other module that reaches for ``get_semaphore`` directly, so
future execs cannot dodge admission control.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional

from spark_rapids_tpu.runtime import telemetry as TM
from spark_rapids_tpu.runtime.semaphore import get_semaphore, peek_semaphore

_TM_SUBMITTED = TM.REGISTRY.labeled_counter(
    "tpuq_scheduler_submitted_total",
    "queries admitted into the scheduler (queued or dispatched)",
    label="tenant")
_TM_COMPLETED = TM.REGISTRY.labeled_counter(
    "tpuq_scheduler_completed_total",
    "queries that finished (released their run slot) per tenant",
    label="tenant")
_TM_REJECTED = TM.REGISTRY.labeled_counter(
    "tpuq_admission_rejected_total",
    "submissions rejected at admission, by structured reason "
    "(shed_* reasons also count in tpuq_admission_shed_total)",
    label="reason")
_TM_SHED = TM.REGISTRY.labeled_counter(
    "tpuq_admission_shed_total",
    "submissions load-shed by watermark breach, per tenant",
    label="tenant")
_TM_CANCELLED_QUEUED = TM.REGISTRY.counter(
    "tpuq_scheduler_cancelled_queued_total",
    "queries cancelled or deadline-expired while still QUEUED "
    "(never admitted to a run slot)")
_TM_QUEUE_WAIT = TM.REGISTRY.histogram(
    "tpuq_scheduler_queue_wait_seconds",
    "queued-to-granted latency per admitted query")
_TM_PREEMPTED = TM.REGISTRY.labeled_counter(
    "tpuq_scheduler_preempted_total",
    "running queries suspended by the preemption arbiter, per victim "
    "tenant", label="tenant")
_TM_SLO_BREACH = TM.REGISTRY.labeled_counter(
    "tpuq_slo_breach_total",
    "sliding-window p99 SLO breach transitions per tenant (entering "
    "the breached state; shedding while breached counts in "
    "tpuq_admission_rejected_total{reason=shed_slo})", label="tenant")
_TM_REMOTE_SUSPENDED = TM.REGISTRY.labeled_counter(
    "tpuq_scheduler_remote_suspended_total",
    "running queries suspended on a cluster arbiter directive (the "
    "cross-executor half of preemption), per victim tenant",
    label="tenant")

# ticket lifecycle (SUSPENDED: granted once, slot reclaimed by the
# preemption arbiter, waiting to resume — resumes before new grants)
QUEUED = "QUEUED"
RUNNING = "RUNNING"
SUSPENDED = "SUSPENDED"
DONE = "DONE"
CANCELLED = "CANCELLED"

#: sanctioned priority band for ``submit`` — out-of-range values are a
#: caller bug surfaced as QueryRejected(reason='bad_priority') at the
#: door, not a KeyError deep in a dispatch lane
PRIORITY_MIN = -100
PRIORITY_MAX = 100

#: rejection reasons that mean "the service is overloaded" (counted in
#: the shed counter + health WARN) as opposed to "this tenant hit its
#: own quota"
SHED_REASONS = frozenset({"shed_queue_depth", "shed_spill_pressure",
                          "shed_semaphore_saturation", "shed_slo",
                          "shed_cluster"})

_TENANT_PREFIX = "spark.rapids.tpu.scheduler.tenant."


class QueryRejected(RuntimeError):
    """Structured admission rejection.  ``reason`` is machine-readable
    (``shed_queue_depth`` / ``shed_spill_pressure`` /
    ``shed_semaphore_saturation`` / ``tenant_queue_full`` /
    ``queue_full`` / ``bad_priority``); callers switch on it to retry,
    back off, fix the request, or fail over to another replica."""

    def __init__(self, reason: str, tenant: Optional[str] = None,
                 detail: str = ""):
        self.reason = reason
        self.tenant = tenant
        self.detail = detail
        msg = f"query rejected at admission: {reason}"
        if tenant is not None:
            msg += f" (tenant={tenant})"
        if detail:
            msg += f" — {detail}"
        super().__init__(msg)


def check_priority(priority, tenant: Optional[str] = None) -> int:
    """Validate a submission priority at the door.  Returns the
    normalized int, or raises ``QueryRejected(reason='bad_priority')``
    for non-integers and values outside [PRIORITY_MIN, PRIORITY_MAX] —
    before any token is minted or scheduler state touched."""
    try:
        p = int(priority)
        if p != priority:  # 2.5, "5", ... — only true ints pass
            p = None
    except (TypeError, ValueError):
        p = None
    if p is None or not (PRIORITY_MIN <= p <= PRIORITY_MAX):
        _TM_REJECTED.inc("bad_priority")
        raise QueryRejected(
            "bad_priority", tenant=tenant,
            detail=f"priority={priority!r} outside "
                   f"[{PRIORITY_MIN}, {PRIORITY_MAX}]")
    return p


class Ticket:
    """One submission's place in the service.  Created by ``submit``;
    the owning worker blocks in ``acquire`` until granted, runs the
    query, then ``release``s the slot."""

    __slots__ = ("query_id", "tenant", "priority", "token", "state",
                 "submitted_at", "granted_at", "suspended_at",
                 "remote_hold")

    def __init__(self, query_id: int, tenant: str, priority: int, token):
        self.query_id = query_id
        self.tenant = tenant
        self.priority = priority
        self.token = token
        self.state = QUEUED
        self.submitted_at = time.monotonic()
        self.granted_at: Optional[float] = None
        self.suspended_at: Optional[float] = None
        # suspended on a CLUSTER arbiter directive: local dispatch must
        # not resume it — only remote_resume (or the suspend lease's
        # expiry) lifts the hold
        self.remote_hold = False


class TenantState:
    """Per-tenant queues, quotas, and accounting.  All mutation happens
    under the owning scheduler's condition lock."""

    __slots__ = ("name", "weight", "max_in_flight", "max_queued",
                 "hbm_share", "run_cap", "lanes", "deficit", "running",
                 "queued", "submitted", "completed", "rejected", "shed",
                 "cancelled_queued", "preempted", "suspended",
                 "slo_p99_ms", "slo_window", "slo_breached",
                 "slo_breaches", "cluster_shed")

    def __init__(self, name: str, weight: float, max_in_flight: int,
                 max_queued: int, hbm_share: float, max_concurrent: int,
                 slo_p99_ms: int = 0, slo_window: int = 64):
        self.name = name
        self.weight = max(0.01, float(weight))
        self.max_in_flight = max(1, int(max_in_flight))
        self.max_queued = max(0, int(max_queued))
        self.hbm_share = min(1.0, max(0.0, float(hbm_share)))
        # the HBM share caps concurrent run slots (each slot may
        # reserve up to the whole pool); always at least 1 so a
        # configured tenant can make progress
        self.run_cap = max(1, min(self.max_in_flight,
                                  math.ceil(self.hbm_share
                                            * max_concurrent)))
        # priority -> FIFO of queued tickets; higher priority drains
        # first, strictly
        self.lanes: Dict[int, deque] = {}
        self.deficit = 0.0
        self.running = 0
        self.queued = 0
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.shed = 0
        self.cancelled_queued = 0
        self.preempted = 0   # times one of this tenant's queries was
        self.suspended = 0   # suspended / currently-suspended count
        # SLO guardrail: sliding window of (wall_s, dominant_bucket)
        # completion samples; 0 target disables tracking
        self.slo_p99_ms = max(0, int(slo_p99_ms))
        self.slo_window: deque = deque(maxlen=max(8, int(slo_window)))
        self.slo_breached = False
        self.slo_breaches = 0
        # cluster arbiter ordered this tenant's submissions shed (the
        # tenant is over its cluster share and nothing preemptible is
        # left) — lifted by an 'unshed' directive or agent re-sync
        self.cluster_shed = False

    def backlogged(self) -> bool:
        return self.queued > 0 and self.running < self.run_cap

    def pop_ticket(self) -> Ticket:
        prio = max(p for p, lane in self.lanes.items() if lane)
        lane = self.lanes[prio]
        ticket = lane.popleft()
        if not lane:
            del self.lanes[prio]
        return ticket

    def remove_ticket(self, ticket: Ticket) -> bool:
        lane = self.lanes.get(ticket.priority)
        if lane is None:
            return False
        try:
            lane.remove(ticket)
        except ValueError:
            return False
        if not lane:
            del self.lanes[ticket.priority]
        return True


class QueryScheduler:
    """The admission controller + fair dispatcher.  One condition
    variable guards all state; dispatch is event-driven (runs inside
    ``submit``/``release``/queued-cancel removal — there is no
    scheduler thread to leak or deadlock).

    Lock order: ``self._cv`` may be held while touching a
    ``CancelToken`` (``check``/``add_waiter``/``request_suspend``/
    ``resume``) — safe because the token lock is a leaf (token
    cancel/suspend paths notify waiter CVs OUTSIDE the token lock),
    and the only foreign CV those notifications take
    (``DeviceSemaphore._cv``) is never held by any thread that wants
    ``self._cv`` — the semaphore layer never calls into the
    scheduler.  The scheduler never takes the memory-manager lock
    while holding ``self._cv`` (the pressure probes read plain
    attributes), and the memory arbiter's
    ``request_tenant_preemption`` upcall must likewise be made
    without the memory lock held.
    """

    def __init__(self, conf=None):
        from spark_rapids_tpu import conf as C
        self._cv = threading.Condition()
        self._conf = conf
        if conf is not None:
            self.max_concurrent = int(conf.get(C.SCHED_MAX_CONCURRENT))
            self.max_queued = int(conf.get(C.SCHED_MAX_QUEUED))
            self.shed_queue_depth = int(conf.get(C.SCHED_SHED_QUEUE_DEPTH))
            self.shed_spill_ratio = float(conf.get(C.SCHED_SHED_SPILL_RATIO))
            self.shed_sem_saturation = float(
                conf.get(C.SCHED_SHED_SEM_SATURATION))
            self._default_weight = float(conf.get(C.SCHED_TENANT_WEIGHT))
            self._default_in_flight = int(
                conf.get(C.SCHED_TENANT_MAX_IN_FLIGHT))
            self._default_queued = int(conf.get(C.SCHED_TENANT_MAX_QUEUED))
            self._default_hbm_share = float(
                conf.get(C.SCHED_TENANT_HBM_SHARE))
            self.preempt_enabled = bool(conf.get(C.SCHED_PREEMPT_ENABLED))
            self.preempt_grace_s = float(
                conf.get(C.SCHED_PREEMPT_GRACE_MS)) / 1000.0
            self.preempt_min_run_s = float(
                conf.get(C.SCHED_PREEMPT_MIN_RUN_MS)) / 1000.0
            self.queue_shaping = bool(conf.get(C.SCHED_QUEUE_SHAPING))
            self._default_slo_ms = int(
                conf.get(C.SCHED_TENANT_SLO_P99_MS))
            self.slo_window = int(conf.get(C.SCHED_SLO_WINDOW))
        else:
            self.max_concurrent = C.SCHED_MAX_CONCURRENT.default
            self.max_queued = C.SCHED_MAX_QUEUED.default
            self.shed_queue_depth = C.SCHED_SHED_QUEUE_DEPTH.default
            self.shed_spill_ratio = C.SCHED_SHED_SPILL_RATIO.default
            self.shed_sem_saturation = C.SCHED_SHED_SEM_SATURATION.default
            self._default_weight = C.SCHED_TENANT_WEIGHT.default
            self._default_in_flight = C.SCHED_TENANT_MAX_IN_FLIGHT.default
            self._default_queued = C.SCHED_TENANT_MAX_QUEUED.default
            self._default_hbm_share = C.SCHED_TENANT_HBM_SHARE.default
            self.preempt_enabled = C.SCHED_PREEMPT_ENABLED.default
            self.preempt_grace_s = C.SCHED_PREEMPT_GRACE_MS.default / 1000.0
            self.preempt_min_run_s = (
                C.SCHED_PREEMPT_MIN_RUN_MS.default / 1000.0)
            self.queue_shaping = C.SCHED_QUEUE_SHAPING.default
            self._default_slo_ms = C.SCHED_TENANT_SLO_P99_MS.default
            self.slo_window = C.SCHED_SLO_WINDOW.default
        self._tenants: Dict[str, TenantState] = {}
        self._rr_order: deque = deque()  # round-robin tie-break rotation
        self._tickets: Dict[int, Ticket] = {}
        self._suspended: List[Ticket] = []  # oldest suspension first
        self.queued_total = 0
        self.running_total = 0

    # -- tenants -----------------------------------------------------------

    def _tenant_override(self, name: str, suffix: str, default):
        if self._conf is None:
            return default
        raw = self._conf.get_raw(f"{_TENANT_PREFIX}{name}.{suffix}")
        if raw is None:
            return default
        try:
            return type(default)(raw)
        except (TypeError, ValueError):
            raise QueryRejected(
                "bad_tenant_conf", tenant=name,
                detail=f"{_TENANT_PREFIX}{name}.{suffix}={raw!r} is not "
                       f"a valid {type(default).__name__}")

    def _tenant_locked(self, name: str) -> TenantState:
        t = self._tenants.get(name)
        if t is None:
            t = TenantState(
                name,
                weight=self._tenant_override(
                    name, "weight", self._default_weight),
                max_in_flight=self._tenant_override(
                    name, "maxInFlight", self._default_in_flight),
                max_queued=self._tenant_override(
                    name, "maxQueued", self._default_queued),
                hbm_share=self._tenant_override(
                    name, "hbmShare", self._default_hbm_share),
                max_concurrent=self.max_concurrent,
                slo_p99_ms=self._tenant_override(
                    name, "sloP99Ms", self._default_slo_ms),
                slo_window=self.slo_window)
            self._tenants[name] = t
            self._rr_order.append(name)
        return t

    # -- admission ---------------------------------------------------------

    def _shed_reason(self) -> Optional[tuple]:
        """(reason, detail) if a service-wide watermark is breached.
        Reads live pressure signals; never creates runtime state."""
        depth = self.queued_total + self.running_total
        if depth >= self.shed_queue_depth:
            return ("shed_queue_depth",
                    f"{depth} queued+running >= shed.queueDepth="
                    f"{self.shed_queue_depth}")
        from spark_rapids_tpu.runtime import memory
        mgr = memory.peek_manager()
        if mgr is not None:
            pressure = mgr.spill_pressure()
            if pressure >= self.shed_spill_ratio:
                return ("shed_spill_pressure",
                        f"host spill tier {pressure:.2f} full >= "
                        f"shed.spillRatio={self.shed_spill_ratio} — "
                        "shedding before the disk tier thrashes")
        sem = peek_semaphore()
        if sem is not None and sem.permits > 0:
            saturation = (sem.holders + sem.waiting) / sem.permits
            if saturation >= self.shed_sem_saturation:
                return ("shed_semaphore_saturation",
                        f"(holders+waiting)/permits={saturation:.2f} >= "
                        "shed.semaphoreSaturation="
                        f"{self.shed_sem_saturation}")
        return None

    def _effective_max_queued_locked(self, t: TenantState) -> int:
        """The tenant's EFFECTIVE queued cap: with queue shaping on,
        its weight share of the global queue budget (so one hot
        tenant's standing queue cannot monopolise admission and bury
        every other tenant's latency behind it), never above its own
        static ``maxQueued``."""
        if not self.queue_shaping:
            return t.max_queued
        total_w = sum(x.weight for x in self._tenants.values())
        share = math.ceil((t.weight / max(total_w, t.weight))
                          * self.max_queued)
        return min(t.max_queued, max(1, share))

    @staticmethod
    def _observed_p99_ms_locked(t: TenantState) -> Optional[float]:
        """Nearest-rank p99 over the tenant's sliding completion
        window (ms); None below the 8-sample confidence floor."""
        if len(t.slo_window) < 8:
            return None
        walls = sorted(w for w, _b in t.slo_window)
        idx = max(0, math.ceil(0.99 * len(walls)) - 1)
        return walls[idx] * 1000.0

    def submit(self, query_id: int, tenant: str = "default",
               priority: int = 0, token=None) -> Ticket:
        """Admit or reject one submission.  Returns a QUEUED ``Ticket``
        (pass it to ``acquire`` from the thread that will run the
        query) or raises ``QueryRejected(reason=...)``.  Never blocks
        beyond the scheduler lock."""
        priority = check_priority(priority, tenant)
        shed = None
        reason = None
        detail = ""
        ticket = None
        with self._cv:
            t = self._tenant_locked(tenant)
            shed = self._shed_reason()
            eff_cap = self._effective_max_queued_locked(t)
            slo_cut = t.slo_breached and t.slo_p99_ms > 0
            if slo_cut:
                # queue-depth shaping while the tenant's p99 breaches
                # its SLO: halve the effective cap so the backlog the
                # breach feeds on drains instead of growing
                eff_cap = max(1, eff_cap // 2)
            if shed is not None:
                reason, detail = shed
                t.shed += 1
                t.rejected += 1
            elif t.cluster_shed:
                reason = "shed_cluster"
                detail = (f"tenant {tenant} shed by cluster arbiter "
                          "directive (over cluster share, nothing left "
                          "to preempt)")
                t.shed += 1
                t.rejected += 1
            elif t.queued >= eff_cap:
                if slo_cut:
                    reason = "shed_slo"
                    detail = (f"tenant p99 SLO breached "
                              f"(target={t.slo_p99_ms}ms) — queue cap "
                              f"shaped to {eff_cap}, {t.queued} queued")
                    t.shed += 1
                else:
                    reason = "tenant_queue_full"
                    detail = (f"{t.queued} queued >= effective cap "
                              f"{eff_cap} (tenant maxQueued="
                              f"{t.max_queued}"
                              + (", weight-shaped" if self.queue_shaping
                                 else "") + ")")
                t.rejected += 1
            elif self.queued_total >= self.max_queued:
                reason = "queue_full"
                detail = (f"{self.queued_total} queued >= "
                          f"maxQueuedQueries={self.max_queued}")
                t.rejected += 1
            else:
                ticket = Ticket(query_id, tenant, int(priority), token)
                t.lanes.setdefault(ticket.priority,
                                   deque()).append(ticket)
                t.queued += 1
                t.submitted += 1
                self.queued_total += 1
                self._tickets[query_id] = ticket
                self._dispatch_locked()
        if reason is not None:
            _TM_REJECTED.inc(reason)
            if reason in SHED_REASONS:
                _TM_SHED.inc(tenant)
                TM.REGISTRY.record_health({
                    "severity": "WARN", "check": "admission_shed",
                    "value": 1, "threshold": 0, "query_id": query_id,
                    "detail": f"tenant={tenant} {detail}"})
            raise QueryRejected(reason, tenant=tenant, detail=detail)
        _TM_SUBMITTED.inc(tenant)
        return ticket

    # -- dispatch ----------------------------------------------------------

    def _dispatch_locked(self) -> None:
        """Grant free run slots: suspended tickets resume FIRST (they
        already won a slot once — preemption borrowed it, it was not
        revoked), then queued tickets are granted fairest-first.
        Tickets flip to RUNNING here (the grant is the state change —
        the acquiring thread merely observes it), so a grant holds even
        if the acquirer is slow to wake."""
        granted = False
        for k in list(self._suspended):
            if self.running_total >= self.max_concurrent:
                break
            if k.remote_hold:
                # a cluster directive parked it — a free LOCAL slot
                # must not resume it (that would undo the cluster
                # share enforcement one heartbeat after it landed)
                continue
            vt = self._tenants[k.tenant]
            if vt.running >= vt.run_cap:
                continue
            self._suspended.remove(k)
            k.state = RUNNING
            k.granted_at = time.monotonic()
            vt.running += 1
            vt.suspended -= 1
            self.running_total += 1
            granted = True
            if k.token is not None:
                # safe under self._cv: resume() only sets the token's
                # resume event — it never notifies foreign CVs
                k.token.resume()
        while (self.running_total < self.max_concurrent
               and self.queued_total > 0):
            ticket = self._next_ticket_locked()
            if ticket is None:
                break
            t = self._tenants[ticket.tenant]
            t.queued -= 1
            t.running += 1
            self.queued_total -= 1
            self.running_total += 1
            ticket.state = RUNNING
            ticket.granted_at = time.monotonic()
            granted = True
        if granted:
            self._cv.notify_all()

    def _next_ticket_locked(self) -> Optional[Ticket]:
        """Deficit weighted round-robin: each full pass over backlogged
        tenants without a grant refills every backlogged tenant's
        deficit by its weight; a grant costs 1.0.  Weight >= 0.01, so
        at most ~100 refill rounds reach a grant — the loop is bounded,
        not heuristic."""
        if not any(t.backlogged() for t in self._tenants.values()):
            return None
        for _round in range(102):
            for _ in range(len(self._rr_order)):
                name = self._rr_order[0]
                self._rr_order.rotate(-1)
                t = self._tenants[name]
                if t.backlogged() and t.deficit >= 1.0:
                    t.deficit -= 1.0
                    return t.pop_ticket()
            for t in self._tenants.values():
                if t.backlogged():
                    t.deficit += t.weight
                else:
                    # an idle tenant must not bank unbounded credit and
                    # later monopolize the device in a burst
                    t.deficit = min(t.deficit, t.weight)
        return None

    # -- preemption arbiter ------------------------------------------------

    def _suspend_locked(self, victim: Ticket, now: float) -> None:
        victim.state = SUSPENDED
        victim.suspended_at = now
        vt = self._tenants[victim.tenant]
        vt.running -= 1
        vt.preempted += 1
        vt.suspended += 1
        self.running_total -= 1
        self._suspended.append(victim)
        _TM_PREEMPTED.inc(victim.tenant)

    def _grant_locked(self, ticket: Ticket, now: float) -> None:
        t = self._tenants[ticket.tenant]
        t.remove_ticket(ticket)
        t.queued -= 1
        t.running += 1
        self.queued_total -= 1
        self.running_total += 1
        ticket.state = RUNNING
        ticket.granted_at = now

    def _maybe_preempt_locked(self, ticket: Ticket,
                              waiting_since: float) -> Optional[Ticket]:
        """The arbiter: when ``ticket`` has starved past
        ``preempt.graceMs`` and no slot can free up on its own, pick a
        victim (largest-runtime query of the most over-share tenant —
        same-tenant victims only on strict priority, cross-tenant only
        when the victim's tenant is more over its fair share than the
        waiter's or the waiter outranks it), suspend it — ticket state
        AND token request in one locked step, so a concurrent dispatch
        can never resume a ticket whose token has not yet heard of the
        suspend — and hand its slot to the waiter atomically.  Returns
        the victim or None."""
        if not self.preempt_enabled:
            return None
        now = time.monotonic()
        if now - waiting_since < self.preempt_grace_s:
            return None
        t = self._tenants[ticket.tenant]
        tenant_capped = t.running >= t.run_cap
        if not tenant_capped and self.running_total < self.max_concurrent:
            return None  # a slot is free — normal dispatch will grant
        waiter_score = t.running / t.weight
        cands = []
        for k in self._tickets.values():
            if k.state != RUNNING or k.token is None:
                continue
            if k.token.cancelled() or k.token.preempt_pending():
                continue
            if (k.granted_at is None
                    or now - k.granted_at < self.preempt_min_run_s):
                continue  # anti-thrash floor: let it make progress
            if k.tenant == ticket.tenant:
                if k.priority >= ticket.priority:
                    continue
            else:
                if tenant_capped:
                    continue  # only evicting our own frees quota room
                kt = self._tenants[k.tenant]
                if (kt.running / kt.weight <= waiter_score
                        and k.priority >= ticket.priority):
                    continue
            cands.append(k)
        if not cands:
            return None

        def _score(k: Ticket):
            kt = self._tenants[k.tenant]
            return (kt.running / kt.weight, now - (k.granted_at or now))

        victim = max(cands, key=_score)
        victim.token.request_suspend(
            f"preempted by query {ticket.query_id} "
            f"(tenant={ticket.tenant}, priority={ticket.priority})")
        self._suspend_locked(victim, now)
        self._grant_locked(ticket, now)
        self._cv.notify_all()
        return victim

    def request_tenant_preemption(self, tenant: str,
                                  exclude_query_id: Optional[int] = None
                                  ) -> bool:
        """HBM-arbiter hook: a tenant breached its byte budget and
        spilling its own residency was not enough — suspend the
        tenant's largest-runtime OTHER running query so its residency
        spills and its reservations unwind.  Call WITHOUT holding the
        memory-manager lock (this takes the scheduler lock).  The
        freed run slot is deliberately NOT re-dispatched here — an
        immediate dispatch would resume the victim straight back into
        it; the next submit/release event hands the slot out."""
        with self._cv:
            if not self.preempt_enabled:
                return False
            now = time.monotonic()
            cands = [
                k for k in self._tickets.values()
                if k.state == RUNNING and k.tenant == tenant
                and k.query_id != exclude_query_id
                and k.token is not None
                and not k.token.cancelled()
                and not k.token.preempt_pending()
                and k.granted_at is not None
                and now - k.granted_at >= self.preempt_min_run_s]
            if not cands:
                return False
            victim = min(cands, key=lambda k: k.granted_at)
            victim.token.request_suspend(
                f"tenant {tenant} HBM budget breach")
            self._suspend_locked(victim, now)
        return True

    # -- cluster tenancy (runtime/tenancy.py drives these) -----------------

    def remote_suspend(self, query_id: int, detail: str = "",
                       ttl_s: Optional[float] = None) -> bool:
        """Suspend one RUNNING query on a cluster arbiter directive.
        Unlike local arbitration this does not need preempt.enabled —
        the operator armed the cluster protocol explicitly.  The token
        suspend is leased (``ttl_s``): if the coordinator stops
        renewing (executor loss, coordinator restart) the token
        force-resumes itself and ``notify_force_resumed`` repairs the
        slot accounting.  Cancel always wins: a cancelled or
        already-pending token refuses the suspend."""
        with self._cv:
            k = self._tickets.get(query_id)
            if k is None or k.state != RUNNING or k.token is None:
                return False
            if k.token.cancelled() or k.token.preempt_pending():
                return False
            if not k.token.request_suspend(detail, ttl_s=ttl_s):
                return False
            k.token._suspend_owner = weakref.ref(self)
            self._suspend_locked(k, time.monotonic())
            k.remote_hold = True
            # hand the freed slot out NOW: unlike the HBM-breach path
            # there may be no later submit/release event on this
            # executor to run dispatch, and the starved waiter this
            # directive exists for is sitting in acquire().  The
            # victim itself cannot bounce back — dispatch skips
            # remote_hold tickets.
            self._dispatch_locked()
            self._cv.notify_all()
        _TM_REMOTE_SUSPENDED.inc(k.tenant)
        return True

    def remote_resume(self, query_id: int) -> bool:
        """Lift a remote hold (cluster 'resume' directive) and let
        normal dispatch resume the ticket when a slot frees."""
        with self._cv:
            k = self._tickets.get(query_id)
            if k is None or not k.remote_hold:
                return False
            k.remote_hold = False
            if k.state == SUSPENDED:
                self._dispatch_locked()
                self._cv.notify_all()
            return True

    def notify_force_resumed(self, query_id: int) -> None:
        """The wedge guard fired: a suspended token's lease expired
        unrenewed and it self-resumed.  Follow it in the ticket
        accounting — the query is running again whether or not a slot
        was free (liveness beats strict capacity; the one-slot
        overshoot drains at the next release)."""
        with self._cv:
            k = self._tickets.get(query_id)
            if k is None or k.state != SUSPENDED:
                return
            k.remote_hold = False
            try:
                self._suspended.remove(k)
            except ValueError:
                pass
            k.state = RUNNING
            k.granted_at = time.monotonic()
            vt = self._tenants[k.tenant]
            vt.running += 1
            vt.suspended -= 1
            self.running_total += 1
            self._cv.notify_all()

    def set_cluster_shed(self, tenant: str, shed: bool) -> None:
        """Apply/lift a cluster 'shed'/'unshed' directive for a
        tenant; shed submissions reject with reason='shed_cluster'."""
        with self._cv:
            self._tenant_locked(tenant).cluster_shed = bool(shed)

    def record_latency(self, tenant: str, wall_s: float,
                       buckets: Optional[dict] = None,
                       query_id: Optional[int] = None
                       ) -> Optional[dict]:
        """Feed one completed query's submit-to-done wall time (and
        its attribution bucket seconds) into the tenant's SLO
        estimator.  Returns a breach record on the un-breached ->
        breached transition (the caller black-box dumps it); None
        otherwise."""
        dominant = ""
        if buckets:
            dominant = max(buckets, key=lambda b: buckets[b])
        breach = None
        with self._cv:
            t = self._tenant_locked(tenant)
            t.slo_window.append((max(0.0, float(wall_s)), dominant))
            if t.slo_p99_ms <= 0:
                return None
            p99 = self._observed_p99_ms_locked(t)
            if p99 is None:
                return None
            if p99 > t.slo_p99_ms:
                if not t.slo_breached:
                    t.slo_breached = True
                    t.slo_breaches += 1
                    doms = [b for _w, b in t.slo_window if b]
                    offending = (max(set(doms), key=doms.count)
                                 if doms else "unattributed")
                    breach = {"tenant": tenant,
                              "observed_p99_ms": round(p99, 3),
                              "slo_p99_ms": t.slo_p99_ms,
                              "dominant_bucket": offending,
                              "window": len(t.slo_window),
                              "query_id": query_id}
            else:
                t.slo_breached = False
        if breach is not None:
            _TM_SLO_BREACH.inc(tenant)
            TM.REGISTRY.record_health({
                "severity": "WARN", "check": "slo_breach",
                "value": breach["observed_p99_ms"],
                "threshold": breach["slo_p99_ms"],
                "query_id": query_id,
                "detail": (f"tenant={tenant} p99 "
                           f"{breach['observed_p99_ms']:.0f}ms > slo "
                           f"{breach['slo_p99_ms']}ms, dominant bucket "
                           f"{breach['dominant_bucket']}")})
        return breach

    def local_tenancy_report(self) -> dict:
        """The per-tenant state an executor piggybacks on its
        rendezvous heartbeat: in-flight/queued depth, starvation age,
        and the largest-runtime running query (the cluster arbiter's
        preferred victim on this executor)."""
        with self._cv:
            now = time.monotonic()
            tenants = {}
            for name, t in self._tenants.items():
                oldest = None
                for lane in t.lanes.values():
                    for k in lane:
                        if oldest is None or k.submitted_at < oldest:
                            oldest = k.submitted_at
                largest_qid = None
                largest_run = 0.0
                for k in self._tickets.values():
                    if (k.tenant != name or k.state != RUNNING
                            or k.token is None or k.token.cancelled()
                            or k.token.preempt_pending()
                            or k.granted_at is None):
                        continue
                    run_s = now - k.granted_at
                    if run_s < self.preempt_min_run_s:
                        continue  # anti-thrash floor holds remotely too
                    if largest_qid is None or run_s > largest_run:
                        largest_qid, largest_run = k.query_id, run_s
                tenants[name] = {
                    "weight": t.weight,
                    "running": t.running,
                    "queued": t.queued,
                    "suspended": t.suspended,
                    "oldest_wait_s": (round(now - oldest, 6)
                                      if oldest is not None else None),
                    "largest_qid": largest_qid,
                    "largest_run_s": round(largest_run, 6),
                }
            return {"slots": self.max_concurrent, "tenants": tenants}

    # -- the worker side ---------------------------------------------------

    def acquire(self, ticket: Ticket) -> float:
        """Block the calling (worker) thread until the ticket is
        granted a run slot; returns seconds spent queued.  The wait is
        cancellable and deadline-aware via the ticket's ``CancelToken``
        — cancel/expiry while still QUEUED raises ``QueryCancelled``
        within ~one poll interval, removes the ticket from its lane,
        and counts ``tpuq_scheduler_cancelled_queued_total``.

        Each poll tick also consults the preemption arbiter: once the
        wait exceeds ``preempt.graceMs`` and no slot can free on its
        own, a running victim is suspended and its slot transferred to
        this ticket in one locked step."""
        tok = ticket.token
        registered = False
        waiting_since = time.monotonic()
        try:
            with self._cv:
                try:
                    while ticket.state == QUEUED:
                        if tok is not None:
                            tok.check()
                            if not registered:
                                tok.add_waiter(self._cv)
                                registered = True
                            timeout = tok.wait_interval()
                        else:
                            timeout = 0.1
                        if self._maybe_preempt_locked(
                                ticket, waiting_since) is not None:
                            continue  # slot transferred — loop exits
                        self._cv.wait(timeout=timeout)
                except BaseException:
                    if ticket.state == QUEUED:
                        self._remove_queued_locked(ticket)
                    raise
        finally:
            if registered:
                tok.remove_waiter(self._cv)
        waited = (ticket.granted_at or time.monotonic()) \
            - ticket.submitted_at
        _TM_QUEUE_WAIT.observe(max(0.0, waited))
        return max(0.0, waited)

    def _remove_queued_locked(self, ticket: Ticket) -> None:
        t = self._tenants.get(ticket.tenant)
        if t is not None and t.remove_ticket(ticket):
            t.queued -= 1
            t.cancelled_queued += 1
            self.queued_total -= 1
            ticket.state = CANCELLED
            self._tickets.pop(ticket.query_id, None)
            _TM_CANCELLED_QUEUED.inc()

    def release(self, ticket: Ticket) -> None:
        """Return the run slot (worker's ``finally``).  Idempotent for
        tickets that never ran (cancelled while queued)."""
        completed = False
        with self._cv:
            if ticket.state == RUNNING:
                ticket.state = DONE
                t = self._tenants[ticket.tenant]
                t.running -= 1
                t.completed += 1
                self.running_total -= 1
                self._tickets.pop(ticket.query_id, None)
                completed = True
                self._dispatch_locked()
                self._cv.notify_all()
            elif ticket.state == SUSPENDED:
                # worker bailed while suspended (cancel/deadline fired
                # in the park) — the suspension already returned the
                # run slot, so only the bookkeeping unwinds here
                ticket.state = DONE
                t = self._tenants[ticket.tenant]
                t.completed += 1
                t.suspended -= 1
                try:
                    self._suspended.remove(ticket)
                except ValueError:
                    pass
                self._tickets.pop(ticket.query_id, None)
                completed = True
                self._cv.notify_all()
            elif ticket.state == QUEUED:
                # worker bailed without acquire() ever raising
                self._remove_queued_locked(ticket)
                self._cv.notify_all()
        if completed:
            _TM_COMPLETED.inc(ticket.tenant)

    # -- introspection -----------------------------------------------------

    def active_queries(self, tenant: Optional[str] = None) -> List[int]:
        """Query ids currently queued or running, optionally filtered
        by tenant, oldest submission first."""
        with self._cv:
            tickets = [k for k in self._tickets.values()
                       if tenant is None or k.tenant == tenant]
        tickets.sort(key=lambda k: k.submitted_at)
        return [k.query_id for k in tickets]

    def ticket_state(self, query_id: int) -> Optional[str]:
        with self._cv:
            ticket = self._tickets.get(query_id)
            return ticket.state if ticket is not None else None

    def stats(self) -> Dict[str, dict]:
        """Per-tenant accounting snapshot — the bench driver records
        this (shed/reject counts per tenant) into every
        TPCH_SF1_CONCURRENCY record."""
        with self._cv:
            return {name: {"weight": t.weight,
                           "run_cap": t.run_cap,
                           "running": t.running,
                           "queued": t.queued,
                           "submitted": t.submitted,
                           "completed": t.completed,
                           "rejected": t.rejected,
                           "shed": t.shed,
                           "cancelled_queued": t.cancelled_queued,
                           "preempted": t.preempted,
                           "suspended": t.suspended,
                           "effective_max_queued":
                               self._effective_max_queued_locked(t),
                           "slo_p99_ms": t.slo_p99_ms,
                           "observed_p99_ms":
                               self._observed_p99_ms_locked(t),
                           "slo_breached": t.slo_breached,
                           "slo_breaches": t.slo_breaches,
                           "cluster_shed": t.cluster_shed}
                    for name, t in self._tenants.items()}


# -- process singleton (mirrors semaphore.py) ------------------------------

_scheduler: Optional[QueryScheduler] = None
_sched_lock = threading.Lock()


def get_scheduler(conf=None) -> QueryScheduler:
    """The process scheduler, created on first use.  A later conf only
    re-tunes the service-wide limits/watermarks in place (existing
    tenants keep the quotas they were created with — tenant state must
    not reset under live queries)."""
    from spark_rapids_tpu import conf as C
    global _scheduler
    with _sched_lock:
        if _scheduler is None:
            _scheduler = QueryScheduler(conf)
        elif conf is not None:
            s = _scheduler
            with s._cv:
                s._conf = conf
                s.max_concurrent = int(conf.get(C.SCHED_MAX_CONCURRENT))
                s.max_queued = int(conf.get(C.SCHED_MAX_QUEUED))
                s.shed_queue_depth = int(
                    conf.get(C.SCHED_SHED_QUEUE_DEPTH))
                s.shed_spill_ratio = float(
                    conf.get(C.SCHED_SHED_SPILL_RATIO))
                s.shed_sem_saturation = float(
                    conf.get(C.SCHED_SHED_SEM_SATURATION))
                s._default_weight = float(conf.get(C.SCHED_TENANT_WEIGHT))
                s._default_in_flight = int(
                    conf.get(C.SCHED_TENANT_MAX_IN_FLIGHT))
                s._default_queued = int(
                    conf.get(C.SCHED_TENANT_MAX_QUEUED))
                s._default_hbm_share = float(
                    conf.get(C.SCHED_TENANT_HBM_SHARE))
                s.preempt_enabled = bool(
                    conf.get(C.SCHED_PREEMPT_ENABLED))
                s.preempt_grace_s = float(
                    conf.get(C.SCHED_PREEMPT_GRACE_MS)) / 1000.0
                s.preempt_min_run_s = float(
                    conf.get(C.SCHED_PREEMPT_MIN_RUN_MS)) / 1000.0
                s.queue_shaping = bool(conf.get(C.SCHED_QUEUE_SHAPING))
                s._default_slo_ms = int(
                    conf.get(C.SCHED_TENANT_SLO_P99_MS))
                s.slo_window = int(conf.get(C.SCHED_SLO_WINDOW))
                s._dispatch_locked()
                s._cv.notify_all()
        return _scheduler


def peek_scheduler() -> Optional[QueryScheduler]:
    """The process scheduler if one exists — never creates (telemetry
    and session introspection must not instantiate runtime state)."""
    return _scheduler


def reset_scheduler() -> None:
    global _scheduler
    with _sched_lock:
        _scheduler = None


@contextlib.contextmanager
def device_hold(conf=None, waited_out: Optional[list] = None):
    """THE sanctioned ``DeviceSemaphore`` acquisition path.  Every
    device-admission hold in the engine goes through here so admission
    control, saturation accounting, and the scheduler's pressure
    signals all see the same traffic; the ``scheduler-bypass`` lint
    rule fails any other module that calls ``get_semaphore``."""
    sem = get_semaphore(conf)
    with sem.hold(waited_out=waited_out):
        yield sem


TM.REGISTRY.gauge(
    "tpuq_scheduler_queue_depth",
    "queries currently waiting for a run slot, all tenants",
    fn=lambda: _scheduler.queued_total if _scheduler is not None else 0)
TM.REGISTRY.gauge(
    "tpuq_scheduler_running",
    "queries currently holding a run slot, all tenants",
    fn=lambda: _scheduler.running_total if _scheduler is not None else 0)
