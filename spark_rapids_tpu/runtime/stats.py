"""Per-operator runtime statistics — the stats plane.

[REF: the reference ships qualification/profiling tools that post-process
event logs into per-query per-operator analyses, and its AQE layer
re-plans from observed map-output statistics] — this module is the one
collection plane all four consumers read from:

* **human**: ``df.explain("analyze")`` renders the plan annotated with
  observed rows/bytes/batches + the PR-1 trace rollup's self-time, and
  ``session.last_query_profile()`` returns the same thing structured;
* **AQE**: exchanges record per-partition row/byte counts here and
  ``TpuAQEShuffleReadExec`` prefers them over a fresh device count;
* **bench gate**: every query appends a profile record to the JSONL
  profile store (``spark.rapids.tpu.stats.storePath``) keyed by a STABLE
  plan-node signature, so ``utils/profile.py diff`` can compare runs;
* **planners** (future): the store survives sessions, so a later run can
  consult observed statistics of the same plan shape.

Collection is attached at every ``ExecNode`` pump boundary by the
``__init_subclass__`` auto-wiring in exec/base.py (the same zero-per-op
mechanism the tracer and the cancellation layer ride).  One collector is
active per query (module global, like runtime/trace.py) — a nested
execution rides the owner's collector.

Cost note: observing a DeviceBatch forces one device sync per pumped
batch (``num_rows_host``); ``level=FULL`` adds one per nullable column
for null ratios.  BASIC keeps the per-batch cost to the row count +
static-shape byte size.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# The stats-field catalog: every key a profile record's per-op entry (or
# exchange summary) may carry.  docs_gen.check_stats_documented asserts
# each name is documented in docs/observability.md — the same
# registry-is-the-doc coupling metrics and confs get.
STATS_FIELDS = {
    "op": "exec class name",
    "sig": "stable plan-node signature (op + tree path + schema)",
    "path": "pre-order tree path of the node (root = '0')",
    "rows_out": "live rows observed leaving the operator",
    "batches_out": "batches observed leaving the operator",
    "bytes_out": "physical bytes of the observed output batches",
    "rows_in": "sum of the children's rows_out",
    "bytes_in": "sum of the children's bytes_out",
    "batches_in": "sum of the children's batches_out",
    "batch_rows_hist": "pow-2 histogram of observed batch row counts",
    "padded_rows": "dead rows the shape plane appended to this "
                   "operator's output batches (bucket padding)",
    "null_ratio": "per-column observed null fraction (level=FULL)",
    "partition_rows": "per-partition live-row counts at an exchange",
    "partition_bytes": "per-partition byte sizes at an exchange",
    "skew_factor": "max/mean over an exchange's partition sizes",
    "skewed": "skew_factor exceeded spark.rapids.tpu.stats.skewThreshold",
    "executors": "executor processes whose counts were merged (ICI)",
    "self_s": "operator self-time from the trace rollup (traced runs)",
    "total_s": "operator total time from the trace rollup (traced runs)",
    "fused": "operator was fused into its consumer's kernel (stays zero)",
    "fused_region": "signature of the enclosing FusedStageExec on the "
                    "synthetic per-member records a fused region emits "
                    "(the member keeps its pre-fusion sig/path, so "
                    "profile diff lines it up with unfused history)",
    "region_ops": "member operators compiled into this fused region's "
                  "single XLA program (FusedStageExec records only)",
    "region_compile_s": "XLA compile seconds observed on this fused "
                        "region's first dispatch (regionCompileTime)",
    "kernel_backend": "kernel-plane backend that produced this "
                      "operator's results (jnp/fused/pallas; 'mixed' "
                      "when dispatches disagreed across batches)",
    "adaptive": "adaptive-plane decisions applied at this operator "
                "(kind + triggering stat + chosen action)",
}

_HIST_CAP = 1 << 30


def _hist_bucket(n: int) -> str:
    """Pow-2 bucket label for a batch row count ("0", "1-2", "3-4",
    "5-8", ...) — coarse enough to stay tiny, fine enough to show
    degenerate batch shapes (the 1-row-per-batch pathology)."""
    if n <= 0:
        return "0"
    hi = 1
    while hi < n and hi < _HIST_CAP:
        hi <<= 1
    return f"{hi // 2 + 1}-{hi}" if hi > 1 else "1"


def skew_factor(counts: Sequence[float]) -> float:
    """max/mean over partition sizes; 1.0 for empty or all-zero (a
    uniform nothing is not skewed)."""
    counts = [float(c) for c in counts]
    if not counts:
        return 1.0
    total = sum(counts)
    if total <= 0:
        return 1.0
    mean = total / len(counts)
    return max(counts) / mean


def merge_partition_counts(per_executor: Iterable[Sequence[int]]
                           ) -> List[int]:
    """Element-wise sum of each executor's per-partition counts — the
    coordinator-side merge for counts that rode a rendezvous allgather.
    Ragged replies are an executor-desync bug; fail loudly."""
    merged: List[int] = []
    for counts in per_executor:
        counts = list(counts)
        if not merged:
            merged = [int(c) for c in counts]
            continue
        if len(counts) != len(merged):
            raise ValueError(
                f"per-executor partition counts disagree on width "
                f"({len(counts)} vs {len(merged)}) — executors ran "
                "different plans")
        for i, c in enumerate(counts):
            merged[i] += int(c)
    return merged


def plan_signature(op: str, path: str, schema) -> str:
    """Stable plan-node signature: op class + pre-order tree path +
    output schema field names.  Deterministic across processes and
    sessions (no ids, no memory addresses), so profile-store records of
    the same plan shape compare across runs."""
    try:
        fields = ",".join(schema.field_names())
    except Exception:
        fields = ""
    return hashlib.sha1(
        f"{path}/{op}({fields})".encode()).hexdigest()[:12]


class NodeStats:
    """Observed statistics of ONE plan node (all partitions).

    Pump threads update concurrently — one lock per node, so unrelated
    nodes never contend (same policy as exec.base.Metric)."""

    __slots__ = ("rows", "batches", "bytes", "hist", "nulls", "observed",
                 "partitions", "partition_unit", "executors", "padded",
                 "kernel_backend", "decisions", "_lock")

    def __init__(self):
        self.rows = 0
        self.batches = 0
        self.bytes = 0
        self.padded = 0
        self.kernel_backend: Optional[str] = None
        self.hist: Dict[str, int] = {}
        # col name -> [null count, rows observed]
        self.nulls: Dict[str, List[int]] = {}
        self.observed = 0  # rows scanned for null ratios
        self.partitions: Optional[List[int]] = None
        self.partition_unit = "rows"
        self.executors = 1
        # adaptive-plane decisions applied at this node, in order
        self.decisions: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def add_batch(self, n: int, nbytes: int,
                  null_counts: Optional[Dict[str, int]] = None) -> None:
        b = _hist_bucket(n)
        with self._lock:
            self.rows += n
            self.batches += 1
            self.bytes += nbytes
            self.hist[b] = self.hist.get(b, 0) + 1
            if null_counts is not None:
                self.observed += n
                for name, nc in null_counts.items():
                    slot = self.nulls.setdefault(name, [0, 0])
                    slot[0] += nc
                    slot[1] += n

    def add_padded(self, n: int) -> None:
        with self._lock:
            self.padded += int(n)

    def set_kernel_backend(self, backend: str) -> None:
        with self._lock:
            if self.kernel_backend is None:
                self.kernel_backend = backend
            elif self.kernel_backend != backend:
                # per-batch fallbacks can land different rungs on one op
                self.kernel_backend = "mixed"

    def set_partitions(self, counts: Sequence[int], unit: str,
                       executors: int = 1) -> None:
        with self._lock:
            self.partitions = [int(c) for c in counts]
            self.partition_unit = unit
            self.executors = executors

    def add_decision(self, kind: str, detail: Dict[str, Any]) -> None:
        with self._lock:
            self.decisions.append({"kind": kind, **detail})


class OpStatsCollector:
    """Stats of ONE query execution, keyed by plan-node identity.

    ``observe`` is called from the auto-wired pump boundary for every
    batch an operator yields; exchanges additionally call
    ``record_partitions`` with their measured per-partition sizes.
    ``report(plan)`` walks the plan pre-order and assembles the profile
    record (zeroed entries for nodes that never pumped — empty inputs
    and fused operators produce valid records, not holes)."""

    def __init__(self, query_id: int, level: str = "BASIC",
                 skew_threshold: float = 2.0):
        self.query_id = query_id
        self.level = str(level).upper()
        self.skew_threshold = float(skew_threshold)
        self._nodes: Dict[int, NodeStats] = {}
        self._refs: List[Any] = []  # keep nodes alive: id() stays unique
        self._lock = threading.Lock()

    # -- recording ----------------------------------------------------------
    def node_stats(self, node) -> NodeStats:
        key = id(node)
        ns = self._nodes.get(key)
        if ns is None:
            with self._lock:
                ns = self._nodes.get(key)
                if ns is None:
                    ns = NodeStats()
                    self._nodes[key] = ns
                    self._refs.append(node)
        return ns

    def observe(self, node, batch) -> None:
        """Record one pumped batch.  Duck-typed over the two batch
        kinds so this module imports neither jax nor the columnar
        layer at module scope."""
        ns = self.node_stats(node)
        sel = getattr(batch, "sel", None)
        if sel is not None:  # DeviceBatch
            n = int(batch.num_rows_host())
            nb = int(batch.nbytes())
            ns.add_batch(n, nb, self._device_nulls(batch, n))
            return
        nr = getattr(batch, "num_rows", None)
        if nr is None:  # unknown batch kind: count it, nothing else
            ns.add_batch(0, 0)
            return
        n = int(nr)
        nb = 0
        cols = getattr(batch, "columns", ())
        for c in cols:
            data = getattr(c, "data", None)
            if data is not None and hasattr(data, "nbytes"):
                nb += int(data.nbytes)
            v = getattr(c, "validity", None)
            if v is not None and hasattr(v, "nbytes"):
                nb += int(v.nbytes)
        ns.add_batch(n, nb, self._host_nulls(batch, n))

    def _device_nulls(self, batch, n: int) -> Optional[Dict[str, int]]:
        if self.level != "FULL" or n == 0:
            return None
        import jax.numpy as jnp
        out: Dict[str, int] = {}
        names = batch.schema.field_names()
        for name, c in zip(names, batch.columns):
            if c.validity is None:
                out[name] = 0
                continue
            out[name] = int(jnp.sum(batch.sel & ~c.valid_mask()))
        return out

    def _host_nulls(self, batch, n: int) -> Optional[Dict[str, int]]:
        if self.level != "FULL" or n == 0:
            return None
        out: Dict[str, int] = {}
        names = batch.schema.field_names()
        for name, c in zip(names, batch.columns):
            v = getattr(c, "validity", None)
            out[name] = 0 if v is None else int((~v).sum())
        return out

    def record_partitions(self, node, counts: Sequence[int],
                          unit: str = "rows",
                          executors: int = 1) -> None:
        """Per-partition sizes measured at an exchange boundary (already
        cluster-merged when ``executors`` > 1)."""
        self.node_stats(node).set_partitions(counts, unit, executors)

    def record_decision(self, node, kind: str,
                        detail: Dict[str, Any]) -> None:
        """One adaptive-plane decision applied at ``node`` (the
        adaptive plane calls this through
        ``adaptive.record_decision``, which also bumps the telemetry
        counter)."""
        self.node_stats(node).add_decision(kind, detail)

    # -- AQE read side ------------------------------------------------------
    def partition_counts(self, node
                         ) -> Optional[Tuple[str, List[int]]]:
        """``(unit, sizes)`` previously recorded for ``node``, or None —
        the shaped-read planner consults this before paying for a fresh
        device count."""
        ns = self._nodes.get(id(node))
        if ns is None or ns.partitions is None:
            return None
        return ns.partition_unit, list(ns.partitions)

    def observed(self, node) -> Optional[Tuple[int, int]]:
        """``(rows, bytes)`` observed leaving ``node`` so far, or None
        when the node never pumped — the batch-retargeting input (the
        adaptive read replans from RECORDED observations, never a
        fresh device sync)."""
        ns = self._nodes.get(id(node))
        if ns is None:
            return None
        return ns.rows, ns.bytes

    # -- reporting ----------------------------------------------------------
    def report(self, plan, rollup: Optional[dict] = None,
               wall_s: Optional[float] = None) -> Dict[str, Any]:
        """The structured profile record: pre-order per-op entries plus
        an exchange skew summary.  ``rollup`` is the PR-1 tracer's
        per-op self/total-time map (absent on untraced runs)."""
        ops: List[dict] = []
        exchanges: List[dict] = []

        def walk(node, path: str):
            ns = self._nodes.get(id(node)) or NodeStats()
            rec: Dict[str, Any] = {
                "op": node.name,
                "sig": plan_signature(node.name, path, node.schema),
                "path": path,
                "rows_out": ns.rows,
                "batches_out": ns.batches,
                "bytes_out": ns.bytes,
                "rows_in": sum(
                    (self._nodes.get(id(c)) or NodeStats()).rows
                    for c in node.children),
                "bytes_in": sum(
                    (self._nodes.get(id(c)) or NodeStats()).bytes
                    for c in node.children),
                "batches_in": sum(
                    (self._nodes.get(id(c)) or NodeStats()).batches
                    for c in node.children),
                "batch_rows_hist": dict(sorted(
                    ns.hist.items(),
                    key=lambda kv: 0 if kv[0] == "0"
                    else int(kv[0].split("-")[0]))),
            }
            if ns.padded:
                rec["padded_rows"] = ns.padded
            if ns.kernel_backend is not None:
                rec["kernel_backend"] = ns.kernel_backend
            fused = getattr(node, "metrics", {}).get("fusedIntoConsumer")
            if fused is not None and fused.value:
                rec["fused"] = True
            if ns.nulls:
                rec["null_ratio"] = {
                    name: round(nc / max(tot, 1), 6)
                    for name, (nc, tot) in sorted(ns.nulls.items())}
            if ns.partitions is not None:
                key = ("partition_rows" if ns.partition_unit == "rows"
                       else "partition_bytes")
                rec[key] = list(ns.partitions)
                sf = skew_factor(ns.partitions)
                rec["skew_factor"] = round(sf, 4)
                rec["skewed"] = sf > self.skew_threshold
                if ns.executors > 1:
                    rec["executors"] = ns.executors
                exchanges.append({
                    "op": rec["op"], "sig": rec["sig"],
                    "path": path,
                    "unit": ns.partition_unit,
                    "partitions": len(ns.partitions),
                    "max": max(ns.partitions, default=0),
                    "total": sum(ns.partitions),
                    "skew_factor": rec["skew_factor"],
                    "skewed": rec["skewed"],
                    "executors": ns.executors,
                })
            if ns.decisions:
                rec["adaptive"] = [dict(d) for d in ns.decisions]
            if rollup:
                r = rollup.get(node.name)
                if r is not None:
                    rec["self_s"] = r.get("self_s")
                    rec["total_s"] = r.get("total_s")
            ops.append(rec)
            members = getattr(node, "fusion_members", None)
            if members:
                rec["region_ops"] = len(members)
                ct = getattr(node, "metrics", {}).get("regionCompileTime")
                if ct is not None and ct.value:
                    rec["region_compile_s"] = round(float(ct.value), 6)
                # synthetic per-member records: each member keeps the
                # signature/path it would have carried unfused, so
                # `profile diff` compares fused runs against unfused
                # history and `top` attributes region time back to the
                # member ops (an even split — the program is one fused
                # dispatch, per-member time has no separate observer)
                share = (rec["self_s"] / len(members)
                         if rec.get("self_s") is not None else None)
                for m in members:
                    mrec: Dict[str, Any] = {
                        "op": m["op"], "sig": m["sig"], "path": m["path"],
                        "fused": True, "fused_region": rec["sig"],
                        "rows_out": 0, "batches_out": 0, "bytes_out": 0,
                        "rows_in": 0, "bytes_in": 0, "batches_in": 0,
                        "batch_rows_hist": {},
                    }
                    if share is not None:
                        mrec["self_s"] = share
                        mrec["total_s"] = share
                    ops.append(mrec)
            for i, c in enumerate(node.children):
                walk(c, f"{path}.{i}")

        walk(plan, "0")
        out: Dict[str, Any] = {
            "record": "profile",
            "version": 1,
            "query_id": self.query_id,
            "level": self.level,
            "skew_threshold": self.skew_threshold,
            "ops": ops,
            "exchanges": exchanges,
        }
        decisions = [{"op": rec["op"], "sig": rec["sig"],
                      "path": rec["path"], **d}
                     for rec in ops for d in rec.get("adaptive", ())]
        if decisions:
            out["adaptive_decisions"] = decisions
        if wall_s is not None:
            out["wall_s"] = wall_s
        return out


# ---------------------------------------------------------------------------
# The active collector — one query at a time owns it
# ---------------------------------------------------------------------------

# Checked on every pump step; a bare module global keeps the off path to
# one attribute load (same shape as trace._ACTIVE).  A nested execution
# (sub-query planned mid-query) rides the owner's collector.
_ACTIVE: Optional[OpStatsCollector] = None
_ACTIVE_LOCK = threading.Lock()


def current() -> Optional[OpStatsCollector]:
    return _ACTIVE


def start_query(query_id: int, level: str = "BASIC",
                skew_threshold: float = 2.0
                ) -> Optional[OpStatsCollector]:
    """Install a fresh collector; returns None when another query
    already owns stats collection (the caller is a nested execution)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            return None
        _ACTIVE = OpStatsCollector(query_id, level=level,
                                   skew_threshold=skew_threshold)
        return _ACTIVE


def end_query(collector: Optional[OpStatsCollector]) -> None:
    global _ACTIVE
    if collector is None:
        return
    with _ACTIVE_LOCK:
        if _ACTIVE is collector:
            _ACTIVE = None


# ---------------------------------------------------------------------------
# The persistent profile store
# ---------------------------------------------------------------------------

def append_profile(path: str, record: Dict[str, Any]) -> None:
    """One JSONL profile record per query; same swallow-to-stderr policy
    as the query event log (observability must never fail the query)."""
    from spark_rapids_tpu.runtime import trace
    trace.append_query_log(path, record)


def load_profiles(path: str) -> List[Dict[str, Any]]:
    """Every profile record in a store file (bad lines are skipped — a
    torn concurrent append must not invalidate the whole store)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out
