"""Process-wide telemetry: metrics registry, sampler, health evaluator.

[REF: sql-plugin/../GpuSemaphore.scala wait metrics,
 spill/SpillFramework.scala accounting, GpuMetrics levels;
 SURVEY §2.2 — the production story this module gives the engine]

PR 1's tracer is *query*-scoped; this module is the *process*-scoped
counterpart: one ``MetricsRegistry`` (``REGISTRY``) holding counters,
gauges, and histograms that every runtime subsystem — the HBM arbiter,
the device semaphore, the kernel cache, the shuffle layer, the
partition-pump pool — updates on its hot path.  Design constraints:

* **cheap on the hot path** — a counter ``inc`` is one lock + one add;
  gauges are usually *pull*-based (a callable reads live state at
  snapshot time, producers pay nothing).
* **import-leaf** — this module imports nothing from the rest of the
  package at module level, so any producer may import it.
* **never fails the query** — sink/IO errors are reported to stderr and
  swallowed, the same policy as ``trace.append_query_log``.

Surfaces:

* ``REGISTRY.snapshot()`` / ``session.metrics_report()`` — in-process.
* background sampler (``spark.rapids.tpu.telemetry.enabled``) — appends
  one JSONL snapshot per ``samplePeriodMs`` to ``sinkPath`` and rewrites
  ``promPath`` with Prometheus text exposition format (scrape the file
  via node_exporter's textfile collector, or serve it).
* query windows (``begin_query`` → ``QueryWindow.finish``) — counter
  deltas per query, fed to the health evaluator whose WARN events land
  in the PR-1 query event log under the same ``query-<id>``.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# seconds-scale latency buckets (semaphore acquires, pump tasks)
DEFAULT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 1.0, 5.0, 30.0)


class Counter:
    """Monotonic counter (Prometheus ``counter``)."""

    __slots__ = ("name", "doc", "_lock", "_value")

    def __init__(self, name: str, doc: str = ""):
        self.name = name
        self.doc = doc
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, v=1) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self):
        return self._value


class LabeledCounter:
    """A counter *family* with one label dimension (Prometheus
    ``name{label="value"}`` children).  ``labels(v)`` returns the child
    ``Counter`` for that label value, creating it on first use — hot
    paths hold the child reference and pay the same one-lock ``inc`` a
    plain counter costs.  The family itself reports the sum of its
    children."""

    __slots__ = ("name", "doc", "label", "_lock", "_children")

    def __init__(self, name: str, doc: str = "", label: str = "domain"):
        self.name = name
        self.doc = doc
        self.label = label
        self._lock = threading.Lock()
        self._children: Dict[str, Counter] = {}

    def child_name(self, value: str) -> str:
        return f'{self.name}{{{self.label}="{value}"}}'

    def labels(self, value: str) -> Counter:
        with self._lock:
            c = self._children.get(value)
            if c is None:
                c = Counter(self.child_name(value), self.doc)
                self._children[value] = c
            return c

    def inc(self, value: str, v=1) -> None:
        self.labels(value).inc(v)

    @property
    def value(self):
        with self._lock:
            return sum(c.value for c in self._children.values())

    def child_values(self) -> Dict[str, float]:
        """label value → count, only children that exist."""
        with self._lock:
            return {lv: c.value for lv, c in self._children.items()}

    def sample_items(self) -> List[Tuple[str, float]]:
        """(exposition sample name, value) per child, sorted."""
        with self._lock:
            return sorted((c.name, c.value)
                          for c in self._children.values())


class Gauge:
    """Point-in-time value; ``fn``-backed gauges pull live state at
    snapshot time so producers never pay a per-update cost."""

    __slots__ = ("name", "doc", "_fn", "_value")

    def __init__(self, name: str, doc: str = "",
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.doc = doc
        self._fn = fn
        self._value = 0

    def set(self, v) -> None:
        self._value = v

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:
                return 0
        return self._value


class Histogram:
    """Fixed cumulative buckets for Prometheus export plus a bounded
    reservoir of recent observations for percentile snapshots."""

    __slots__ = ("name", "doc", "buckets", "_lock", "_bucket_counts",
                 "count", "sum", "min", "max", "_reservoir", "_rpos",
                 "_rcap")

    def __init__(self, name: str, doc: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                 reservoir: int = 512):
        self.name = name
        self.doc = doc
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf last
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._reservoir: List[float] = []
        self._rpos = 0
        self._rcap = reservoir

    def observe(self, v: float) -> None:
        with self._lock:
            self._bucket_counts[bisect.bisect_left(self.buckets, v)] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            if len(self._reservoir) < self._rcap:
                self._reservoir.append(v)
            else:  # bounded ring of the most recent observations
                self._reservoir[self._rpos] = v
                self._rpos = (self._rpos + 1) % self._rcap

    def percentile(self, q: float) -> float:
        with self._lock:
            if not self._reservoir:
                return 0.0
            s = sorted(self._reservoir)
            return s[min(len(s) - 1, int(q * len(s)))]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if not self.count:
                return {"count": 0, "sum": 0.0}
            s = sorted(self._reservoir)

            def pct(q):
                return s[min(len(s) - 1, int(q * len(s)))]

            return {"count": self.count, "sum": round(self.sum, 9),
                    "min": round(self.min, 9), "max": round(self.max, 9),
                    "p50": round(pct(0.50), 9),
                    "p95": round(pct(0.95), 9),
                    "p99": round(pct(0.99), 9)}

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs ending at +Inf."""
        with self._lock:
            out, acc = [], 0
            for ub, c in zip(self.buckets, self._bucket_counts):
                acc += c
                out.append((ub, acc))
            out.append((math.inf, self.count))
            return out


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return format(f, ".10g")


class MetricsRegistry:
    """Name → metric; registration is idempotent (same name returns the
    existing instance) so producer modules may register at import time
    in any order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        self._health: List[dict] = []  # recent health events (bounded)
        self.HEALTH_CAP = 64

    def _get_or_create(self, name, kind, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {kind.__name__}")
            return m

    def counter(self, name: str, doc: str = "") -> Counter:
        return self._get_or_create(name, Counter,
                                   lambda: Counter(name, doc))

    def labeled_counter(self, name: str, doc: str = "",
                        label: str = "domain") -> LabeledCounter:
        return self._get_or_create(
            name, LabeledCounter, lambda: LabeledCounter(name, doc, label))

    def gauge(self, name: str, doc: str = "",
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        return self._get_or_create(name, Gauge,
                                   lambda: Gauge(name, doc, fn))

    def histogram(self, name: str, doc: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(name, Histogram,
                                   lambda: Histogram(name, doc, buckets))

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def catalog(self) -> Dict[str, Tuple[str, str]]:
        """name → (kind, doc) — the drift check's source of truth."""
        with self._lock:
            return {n: (type(m).__name__.lower(), m.doc)
                    for n, m in sorted(self._metrics.items())}

    def snapshot(self) -> Dict[str, object]:
        """Flat name → value (histograms: summary dicts)."""
        with self._lock:
            metrics = list(self._metrics.items())
        out = {}
        for name, m in sorted(metrics):
            if isinstance(m, Histogram):
                out[name] = m.snapshot()
            elif isinstance(m, LabeledCounter):
                for child, v in m.sample_items():
                    out[child] = v
            else:
                out[name] = m.value
        return out

    def counter_values(self) -> Dict[str, float]:
        """Plain counters by name plus every labeled-family child by its
        exposition sample name (``name{label="v"}``) — the flat space
        query windows diff."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, float] = {}
        for m in metrics:
            if isinstance(m, Counter):
                out[m.name] = m.value
            elif isinstance(m, LabeledCounter):
                for child, v in m.sample_items():
                    out[child] = v
        return out

    def prometheus_text(self) -> str:
        """Text exposition format: one HELP/TYPE pair per family, then
        the samples; histograms expand to _bucket/_sum/_count."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines = []
        for name, m in metrics:
            doc = (m.doc or name).replace("\\", "\\\\").replace(
                "\n", "\\n")
            lines.append(f"# HELP {name} {doc}")
            if isinstance(m, LabeledCounter):
                lines.append(f"# TYPE {name} counter")
                for child, v in m.sample_items():
                    lines.append(f"{child} {_fmt(v)}")
            elif isinstance(m, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(m.value)}")
            else:
                lines.append(f"# TYPE {name} histogram")
                for ub, acc in m.cumulative_buckets():
                    lines.append(
                        f'{name}_bucket{{le="{_fmt(ub)}"}} {acc}')
                lines.append(f"{name}_sum {_fmt(m.sum)}")
                lines.append(f"{name}_count {m.count}")
        return "\n".join(lines) + "\n"

    def record_health(self, event: dict) -> None:
        with self._lock:
            self._health.append(event)
            if len(self._health) > self.HEALTH_CAP:
                del self._health[:-self.HEALTH_CAP]
        # flight recorder (runtime/attribution.py): health verdicts
        # join the active query's black-box ring.  Lazy import —
        # attribution imports this module at its top level.
        from spark_rapids_tpu.runtime import attribution
        attribution.record_event("health", dict(event))

    def recent_health(self) -> List[dict]:
        with self._lock:
            return list(self._health)


REGISTRY = MetricsRegistry()

# registry-owned metrics (producers own the rest)
_QUERIES = REGISTRY.counter(
    "tpuq_queries_total", "queries executed (toArrow/collect)")
_HEALTH_WARNS = REGISTRY.counter(
    "tpuq_health_warn_total", "health-evaluator WARN events emitted")


def ensure_producers() -> None:
    """Import every producer module so its registrations exist — the
    complete catalog for ``metrics_report`` and the docs drift check
    (registration is import-time; a cold process that never shuffled
    would otherwise miss the shuffle family)."""
    import importlib
    for mod in ("runtime.cancel", "runtime.memory", "runtime.semaphore",
                "runtime.scheduler", "runtime.attribution",
                "runtime.kernel_cache", "runtime.resilience",
                "runtime.lockdep", "runtime.shapes", "adaptive",
                "shuffle.manager", "shuffle.exchange",
                "parallel.executor", "parallel.shuffle",
                "parallel.rendezvous", "exec.distributed",
                "kernels", "cache", "fusion"):
        try:
            importlib.import_module(f"spark_rapids_tpu.{mod}")
        except Exception as e:  # never fail a report over one producer
            print(f"telemetry: cannot import producer {mod}: {e}",
                  file=sys.stderr)


# ---------------------------------------------------------------------------
# sinks: JSONL time series + Prometheus text dump
# ---------------------------------------------------------------------------

def flush_sinks(sink_path: str, prom_path: str) -> None:
    """One snapshot: append a JSONL record, rewrite the prom dump
    atomically.  IO failures must never fail the caller."""
    snap = REGISTRY.snapshot()
    ts = time.time()
    if sink_path:
        try:
            d = os.path.dirname(sink_path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(sink_path, "a") as f:
                f.write(json.dumps(
                    {"ts": time.strftime("%Y-%m-%dT%H:%M:%S%z",
                                         time.localtime(ts)),
                     "unix_ms": int(ts * 1000),
                     "metrics": snap}) + "\n")
        except OSError as e:
            print(f"telemetry: cannot append {sink_path}: {e}",
                  file=sys.stderr)
    if prom_path:
        try:
            d = os.path.dirname(prom_path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = prom_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(REGISTRY.prometheus_text())
            os.replace(tmp, prom_path)
        except OSError as e:
            print(f"telemetry: cannot write {prom_path}: {e}",
                  file=sys.stderr)


class TelemetrySampler(threading.Thread):
    """Daemon thread flushing the sinks every ``period_s``."""

    def __init__(self, period_s: float, sink_path: str, prom_path: str):
        super().__init__(name="tpuq-telemetry", daemon=True)
        self.period_s = max(0.01, period_s)
        self.sink_path = sink_path
        self.prom_path = prom_path
        # NB: not named _stop — Thread.join() calls a private method of
        # that name on CPython
        self._halt = threading.Event()

    def run(self):
        flush_sinks(self.sink_path, self.prom_path)
        # cancel-exempt: daemon sampler, no query scope — halts via its own event
        while not self._halt.wait(self.period_s):
            flush_sinks(self.sink_path, self.prom_path)

    def stop(self, final_flush: bool = True):
        self._halt.set()
        self.join(timeout=5)
        if final_flush:
            flush_sinks(self.sink_path, self.prom_path)


_sampler: Optional[TelemetrySampler] = None
_sampler_lock = threading.Lock()


def configure_sampler(conf) -> Optional[TelemetrySampler]:
    """Start (or retarget) the process sampler per session conf; a conf
    with telemetry disabled leaves a running sampler alone (another
    session owns it)."""
    from spark_rapids_tpu import conf as C
    global _sampler
    if not conf.get(C.TELEMETRY_ENABLED):
        return _sampler
    ensure_producers()
    period = float(conf.get(C.TELEMETRY_PERIOD_MS)) / 1000.0
    sink = str(conf.get(C.TELEMETRY_SINK_PATH))
    prom = str(conf.get(C.TELEMETRY_PROM_PATH))
    with _sampler_lock:
        s = _sampler
        if (s is not None and s.is_alive()
                and (s.period_s, s.sink_path, s.prom_path)
                == (max(0.01, period), sink, prom)):
            return s
        if s is not None:
            s.stop(final_flush=False)
        _sampler = TelemetrySampler(period, sink, prom)
        _sampler.start()
        return _sampler


def stop_sampler() -> None:
    global _sampler
    with _sampler_lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None


# ---------------------------------------------------------------------------
# query windows + health evaluation
# ---------------------------------------------------------------------------

class QueryWindow:
    """Counter snapshot at query start; ``finish()`` yields the deltas
    this query contributed to the process-cumulative counters."""

    def __init__(self, query_id: int):
        self.query_id = query_id
        self.t0 = time.perf_counter()
        self._start = REGISTRY.counter_values()

    def finish(self) -> Tuple[Dict[str, float], float]:
        elapsed = time.perf_counter() - self.t0
        now = REGISTRY.counter_values()
        deltas = {}
        for name, v in now.items():
            d = v - self._start.get(name, 0)
            if d:
                deltas[name] = round(d, 9) if isinstance(d, float) else d
        return deltas, elapsed


def begin_query(query_id: int) -> QueryWindow:
    """Open a telemetry window and a semaphore stats window KEYED by
    this query id (overlapping queries each get their own
    ``max_holders``/``wait_time`` — the registry keeps the cumulative
    view).  Re-entrant under concurrency: every piece of per-query
    state this boundary touches is either per-``QueryWindow`` instance
    or keyed by ``query_id``; the only process-wide effect is the
    legacy serial-query semaphore window, which keyed readers ignore."""
    _QUERIES.inc()
    from spark_rapids_tpu.runtime import semaphore as SEM
    sem = SEM.peek_semaphore()
    if sem is not None:
        sem.begin_query_stats(query_id)
    return QueryWindow(query_id)


def evaluate_health(deltas: Dict[str, float], elapsed_s: float, conf,
                    query_id: Optional[int] = None) -> List[dict]:
    """Threshold checks over one query's counter deltas.  Each breach
    is a structured WARN recorded in the registry and returned for the
    query event log [REF: the reference's driver-log WARN lines for
    spill/retry storms, machine-readable]."""
    from spark_rapids_tpu import conf as C
    events = []

    def warn(check, value, threshold, detail):
        events.append({"severity": "WARN", "check": check,
                       "value": value, "threshold": threshold,
                       "query_id": query_id, "detail": detail})

    spill = (deltas.get("tpuq_spill_host_bytes_total", 0)
             + deltas.get("tpuq_spill_disk_bytes_total", 0))
    reserved = deltas.get("tpuq_hbm_reserve_bytes_total", 0)
    if spill:
        ratio = spill / reserved if reserved else math.inf
        thr = float(conf.get(C.HEALTH_SPILL_RATIO))
        if ratio > thr:
            warn("spill_ratio", round(min(ratio, 1e9), 6), thr,
                 f"spilled {spill} B against {reserved} B reserved — "
                 "working set exceeds the HBM budget; raise poolSize / "
                 "lower batchRows")
    wait = deltas.get("tpuq_semaphore_wait_seconds_total", 0.0)
    if wait and elapsed_s > 0:
        ratio = wait / elapsed_s
        thr = float(conf.get(C.HEALTH_SEM_WAIT_RATIO))
        if ratio > thr:
            warn("semaphore_saturation", round(ratio, 6), thr,
                 f"tasks blocked {wait:.3f}s on device admission over a "
                 f"{elapsed_s:.3f}s query — concurrentGpuTasks is the "
                 "bottleneck")
    compiles = deltas.get("tpuq_kernel_compile_total", 0)
    thr = int(conf.get(C.HEALTH_COMPILE_STORM))
    if compiles > thr:
        warn("compile_storm", compiles, thr,
             f"{compiles} XLA compiles in one query — shape buckets or "
             "expression fingerprints are not being reused")
    degraded = deltas.get("tpuq_host_degraded_ops_total", 0)
    if degraded:
        warn("host_degraded", degraded, 0,
             f"{degraded} device step(s) re-ran on the host path after "
             "retry exhaustion tripped a circuit breaker — see "
             "docs/resilience.md")
    shed = sum(v for name, v in deltas.items()
               if name.startswith("tpuq_admission_shed_total"))
    if shed:
        warn("admission_shed", shed, 0,
             f"{shed} submission(s) were load-shed by admission control "
             "while this query ran — the service is saturated; see "
             "docs/serving.md for the watermark tuning guide")
    for e in events:
        _HEALTH_WARNS.inc()
        REGISTRY.record_health(e)
    return events
