"""Device-admission semaphore — the ``GpuSemaphore`` analog.

[REF: sql-plugin/../GpuSemaphore.scala :: GpuSemaphore] — the reference
gates how many Spark task threads may hold the GPU concurrently
(``spark.rapids.sql.concurrentGpuTasks``) so device memory working sets
don't multiply by the executor's task slots.  Same design here: the
DataFrame partition pump runs partitions on a thread pool (the task-slot
analog), and each partition's device work must hold a permit.  Cumulative
wait time is exposed as the ``semaphoreWaitTime`` metric.

One process-wide semaphore object lives for the process (never swapped —
swapping under a waiter would let two queries admit through different
instances and break the cap); a conf with a different permit count
resizes it in place under its own condition variable.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Optional

from spark_rapids_tpu.runtime import telemetry as TM

_TM_WAIT = TM.REGISTRY.counter(
    "tpuq_semaphore_wait_seconds_total",
    "seconds tasks spent blocked on device admission (cumulative)")
_TM_ACQUIRE = TM.REGISTRY.histogram(
    "tpuq_semaphore_acquire_seconds",
    "per-acquire device-admission wait")

# per-THREAD stack of live permits ({"sem", "tok", "released"}), most
# recent last.  The preemption plane's suspend provider walks it to
# hand a suspending query's permits back to the semaphore and take
# them back on resume; ``release()`` pops it so a permit returned at
# suspension is not double-released by the enclosing ``hold()``.
_TLS = threading.local()


def _tls_entries() -> list:
    entries = getattr(_TLS, "entries", None)
    if entries is None:
        entries = _TLS.entries = []
    return entries


class DeviceSemaphore:
    """Counting semaphore with in-place resize + wait accounting.

    Per-query stats are keyed **by query id** (``begin_query_stats`` /
    ``end_query_stats``): each open window tracks the high-water holder
    count observed while that query was in flight and the wait time its
    OWN tasks spent blocked (attributed via the acquiring thread's
    CancelToken), so overlapping queries no longer bleed stats into
    each other.  The legacy ``max_holders``/``wait_time`` attributes
    remain the serial-query view — reset at each query boundary, last
    boundary wins — for callers that predate concurrent execution.  The
    registry's ``tpuq_semaphore_*`` counters and the ``peak_holders``
    attribute keep the process-lifetime view.
    """

    # open per-query windows beyond this evict oldest-first (a window
    # whose query died without end_query_stats must not leak forever)
    QUERY_WINDOW_CAP = 256

    def __init__(self, permits: int):
        self._cv = threading.Condition()
        self.permits = max(1, int(permits))
        self.holders = 0          # currently admitted tasks
        self.waiting = 0          # tasks currently blocked in acquire
        self.max_holders = 0      # high-water mark (query window)
        self.wait_time = 0.0      # seconds blocked (query window)
        self.peak_holders = 0     # high-water mark (process lifetime)
        # query_id -> {"max_holders": int, "wait_time": float}
        self._windows: "OrderedDict[int, dict]" = OrderedDict()

    def resize(self, permits: int) -> None:
        with self._cv:
            self.permits = max(1, int(permits))
            self._cv.notify_all()

    def acquire(self) -> float:
        """Block until admitted; returns seconds spent waiting (0.0 on
        the uncontended fast path — only actual blocking counts, so an
        unconstrained run reports exactly zero wait).

        The wait is deadline-aware, cancellable, AND preempt-aware: it
        parks at most the active CancelToken's poll interval per
        ``wait()`` (and registers with the token so a cancel or a
        suspend request wakes it immediately), raising
        ``QueryCancelled`` without admitting, and refusing admission
        while the query's token has a suspend pending (a suspended
        query must not re-enter the device behind the preemptor's
        back).  The admitted permit is pushed on the calling thread's
        permit stack so the preemption plane can hand it back at a
        suspend and reacquire it on resume."""
        from spark_rapids_tpu.runtime import cancel
        tok = cancel.current()
        waited = self._wait_admit(tok)
        _tls_entries().append(
            {"sem": self, "tok": tok, "released": False})
        return waited

    def _wait_admit(self, tok) -> float:
        """The wait loop + admission accounting (no permit-stack push)
        — shared by ``acquire`` and the suspend provider's resume
        reacquire.  Wait accounting uses the monotonic clock and sums
        only time actually spent blocked in the condition wait — time
        awake between a spurious wakeup and re-blocking is not wait
        (the old single start/stop stamp inflated
        ``semaphoreWaitTime`` under contention)."""
        from spark_rapids_tpu.runtime import trace
        waited = 0.0
        registered = False
        blocked = False
        wait_span = None
        try:
            with self._cv:
                try:
                    while (self.holders >= self.permits
                           or (tok is not None and tok.preempt_pending())):
                        if not blocked:
                            blocked = True
                            self.waiting += 1
                            # attribution: the blocked path (and only
                            # it) opens a span so the wait lands in the
                            # semaphore_wait bucket on the timeline —
                            # the uncontended acquire stays span-free
                            wait_tr = trace.current()
                            if wait_tr is not None:
                                wait_span = wait_tr.begin(
                                    "DeviceSemaphore", "semaphoreWait")
                        if tok is not None:
                            tok.check()
                            if (tok.preempt_pending()
                                    and tok._suspend_expired()):
                                # wedge guard: the suspension lease
                                # expired while this thread was parked
                                # here (not in _park_suspended, where
                                # the guard otherwise lives) — a dead
                                # requester must never wedge a
                                # semaphore waiter.  Drop our CV around
                                # the force-resume: it repairs slot
                                # accounting under the scheduler lock,
                                # and scheduler code notifies this CV
                                # while holding that lock — keeping the
                                # lock order one-directional.
                                self._cv.release()
                                try:
                                    tok._force_resume()
                                finally:
                                    self._cv.acquire()
                                continue
                            if not registered:
                                tok.add_waiter(self._cv)
                                registered = True
                            timeout = tok.wait_interval()
                        else:
                            # bounded even without a token: a token
                            # opened by a later query must never find
                            # this thread parked in an unbounded wait
                            timeout = 0.1
                        t0 = time.monotonic()
                        self._cv.wait(timeout=timeout)
                        waited += time.monotonic() - t0
                finally:
                    if blocked:
                        self.waiting -= 1
                    if wait_span is not None:
                        wait_tr.end(wait_span)
                self.holders += 1
                self.max_holders = max(self.max_holders, self.holders)
                self.peak_holders = max(self.peak_holders, self.holders)
                self.wait_time += waited
                for w in self._windows.values():
                    if self.holders > w["max_holders"]:
                        w["max_holders"] = self.holders
                if waited and tok is not None and tok.query_id is not None:
                    w = self._windows.get(tok.query_id)
                    if w is not None:
                        w["wait_time"] += waited
        finally:
            if registered:
                tok.remove_waiter(self._cv)
            if waited:
                _TM_WAIT.inc(waited)
            _TM_ACQUIRE.observe(waited)
        return waited

    def begin_query_stats(self, query_id: Optional[int]) -> None:
        """Open a per-query stats window keyed by ``query_id`` AND
        restart the legacy serial-query window (``max_holders`` /
        ``wait_time``): the high-water mark restarts from the holders
        still admitted, the wait clock from zero."""
        with self._cv:
            self.max_holders = self.holders
            self.wait_time = 0.0
            if query_id is not None:
                self._windows[query_id] = {"max_holders": self.holders,
                                           "wait_time": 0.0}
                while len(self._windows) > self.QUERY_WINDOW_CAP:
                    self._windows.popitem(last=False)

    def end_query_stats(self, query_id: Optional[int]) -> Optional[dict]:
        """Close a keyed window and return its stats (None when no
        window is open for that id)."""
        if query_id is None:
            return None
        with self._cv:
            return self._windows.pop(query_id, None)

    def query_stats(self, query_id: int) -> Optional[dict]:
        """Peek an open keyed window without closing it."""
        with self._cv:
            w = self._windows.get(query_id)
            return dict(w) if w is not None else None

    def reset_query_stats(self) -> None:
        """Legacy (serial-query) boundary: restart the un-keyed window
        only."""
        self.begin_query_stats(None)

    def release(self) -> None:
        """Return the calling thread's most recent permit for this
        semaphore.  If that permit was already handed back at a
        suspension (entry marked ``released`` by the preempt plane and
        never reacquired — the query was cancelled mid-suspend) the
        release is a no-op, keeping ``hold()`` balanced.  A release
        with no matching stack entry (cross-thread release on another
        thread's behalf — a legacy pattern some callers use) falls
        through to the raw release."""
        entries = _tls_entries()
        for i in range(len(entries) - 1, -1, -1):
            e = entries[i]
            if e["sem"] is self:
                entries.pop(i)
                if e["released"]:
                    return
                break
        self._release_raw()

    def _release_raw(self) -> None:
        with self._cv:
            self.holders -= 1
            self._cv.notify()

    @contextlib.contextmanager
    def hold(self, waited_out: Optional[list] = None):
        w = self.acquire()
        if waited_out is not None:
            waited_out.append(w)
        try:
            yield self
        finally:
            self.release()


_semaphore: Optional[DeviceSemaphore] = None
_sem_lock = threading.Lock()


def get_semaphore(conf=None) -> DeviceSemaphore:
    """The process semaphore, sized by
    ``spark.rapids.sql.concurrentGpuTasks`` (resized in place when a
    session asks for a different count)."""
    global _semaphore
    permits = None
    if conf is not None:
        from spark_rapids_tpu import conf as C
        permits = conf.get(C.CONCURRENT_TASKS)
    with _sem_lock:
        if _semaphore is None:
            _semaphore = DeviceSemaphore(permits or 2)
        elif permits is not None and permits != _semaphore.permits:
            _semaphore.resize(permits)
        return _semaphore


def peek_semaphore() -> Optional[DeviceSemaphore]:
    """The process semaphore if one exists — never creates (telemetry
    must not instantiate runtime state as a side effect)."""
    return _semaphore


def reset_semaphore() -> None:
    global _semaphore
    with _sem_lock:
        _semaphore = None


# -- preemption suspend provider --------------------------------------
# A suspending thread hands back every permit it holds for the
# suspending query (oldest-first release order is irrelevant — they are
# all returned) and reacquires them in original order on resume.  The
# opaque state is the list of this thread's stack entries released.

def _suspend_thread_permits(token):
    entries = [e for e in _tls_entries()
               if e["tok"] is token and not e["released"]]
    if not entries:
        return None
    for e in entries:
        e["released"] = True
        e["sem"]._release_raw()
    return entries


def _resume_thread_permits(token, state):
    from spark_rapids_tpu.runtime import cancel
    for e in state:
        try:
            e["sem"]._wait_admit(token)
        except cancel.QueryCancelled:
            # permits stay released; the enclosing hold()s see the
            # ``released`` flag and no-op their release, so the permit
            # count stays balanced on the cancel path
            return
        e["released"] = False


from spark_rapids_tpu.runtime import cancel as _cancel  # noqa: E402

_cancel.register_suspend_provider(_suspend_thread_permits,
                                  _resume_thread_permits)


TM.REGISTRY.gauge(
    "tpuq_semaphore_holders", "tasks currently holding a permit",
    fn=lambda: _semaphore.holders if _semaphore is not None else 0)
TM.REGISTRY.gauge(
    "tpuq_semaphore_waiting",
    "tasks currently blocked waiting for a permit (the admission "
    "controller's saturation signal)",
    fn=lambda: _semaphore.waiting if _semaphore is not None else 0)
TM.REGISTRY.gauge(
    "tpuq_semaphore_holders_peak",
    "process-lifetime peak concurrent holders",
    fn=lambda: _semaphore.peak_holders if _semaphore is not None else 0)
TM.REGISTRY.gauge(
    "tpuq_semaphore_permits", "configured concurrent-task permits",
    fn=lambda: _semaphore.permits if _semaphore is not None else 0)
