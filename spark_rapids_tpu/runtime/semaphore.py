"""Device-admission semaphore — the ``GpuSemaphore`` analog.

[REF: sql-plugin/../GpuSemaphore.scala :: GpuSemaphore] — the reference
gates how many Spark task threads may hold the GPU concurrently
(``spark.rapids.sql.concurrentGpuTasks``) so device memory working sets
don't multiply by the executor's task slots.  Same design here: the
DataFrame partition pump runs partitions on a thread pool (the task-slot
analog), and each partition's device work must hold a permit.  Cumulative
wait time is exposed as the ``semaphoreWaitTime`` metric.

One process-wide semaphore object lives for the process (never swapped —
swapping under a waiter would let two queries admit through different
instances and break the cap); a conf with a different permit count
resizes it in place under its own condition variable.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional


class DeviceSemaphore:
    """Counting semaphore with in-place resize + wait accounting."""

    def __init__(self, permits: int):
        self._cv = threading.Condition()
        self.permits = max(1, int(permits))
        self.holders = 0          # currently admitted tasks
        self.max_holders = 0      # high-water mark (test observability)
        self.wait_time = 0.0      # cumulative seconds spent blocked

    def resize(self, permits: int) -> None:
        with self._cv:
            self.permits = max(1, int(permits))
            self._cv.notify_all()

    def acquire(self) -> float:
        """Block until admitted; returns seconds spent waiting."""
        t0 = time.perf_counter()
        with self._cv:
            while self.holders >= self.permits:
                self._cv.wait()
            self.holders += 1
            self.max_holders = max(self.max_holders, self.holders)
            waited = time.perf_counter() - t0
            self.wait_time += waited
        return waited

    def release(self) -> None:
        with self._cv:
            self.holders -= 1
            self._cv.notify()

    @contextlib.contextmanager
    def hold(self, waited_out: Optional[list] = None):
        w = self.acquire()
        if waited_out is not None:
            waited_out.append(w)
        try:
            yield self
        finally:
            self.release()


_semaphore: Optional[DeviceSemaphore] = None
_sem_lock = threading.Lock()


def get_semaphore(conf=None) -> DeviceSemaphore:
    """The process semaphore, sized by
    ``spark.rapids.sql.concurrentGpuTasks`` (resized in place when a
    session asks for a different count)."""
    global _semaphore
    permits = None
    if conf is not None:
        from spark_rapids_tpu import conf as C
        permits = conf.get(C.CONCURRENT_TASKS)
    with _sem_lock:
        if _semaphore is None:
            _semaphore = DeviceSemaphore(permits or 2)
        elif permits is not None and permits != _semaphore.permits:
            _semaphore.resize(permits)
        return _semaphore


def reset_semaphore() -> None:
    global _semaphore
    with _sem_lock:
        _semaphore = None
